"""TPUPlacer: batched placement behind SchedulerAlgorithm="tpu-binpack"
(the new algorithm value plugging into the reference's enum,
nomad/structs/operator.go:199-255).

Lowering strategy per evaluation:
  1. one ClusterTensors build (nodes + proposed usage),
  2. per task group: host-precompiled feasibility/affinity/spread arrays,
     device/core count columns, and distinct_property cap tables,
  3. one jitted solve_task_group scan placing all of the group's
     requests with full cross-placement visibility,
  4. commits mapped back through the scheduler's commit callback so the
     plan object and ctx.proposed_allocs stay authoritative. Exact port
     numbers, device instance ids, and core ids are assigned host-side
     per chosen node after the solve (counts were fit on-device).

Preemption stays host-side: when the kernel finds no fit and preemption
is enabled, the per-request fallback runs the host NodeScorer preemption
path (reference rank.go:205-587's preemption fallback arm). A request
whose post-solve id assignment fails (NUMA "require" mispredicted by
count-fit, overlapping device asks) falls back to the host selector for
that request alone.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs import TRACER
from ..structs import Job, Node, enums
from ..scheduler.context import EvalContext
from ..scheduler.rank import NodeScorer, RankedNode, select_best_node
from ..scheduler.reconcile import PlacementRequest
from .cluster import ClusterTensors, build_task_group_tensors, _pad_pow2


def _binpack_fitness_np(available: np.ndarray, used: np.ndarray) -> np.ndarray:
    """Vectorized BestFit-v3 fit score (reference funcs.go:236
    ScoreFitBinPack), shared by the preemption pick mirror and the bulk
    trajectory mean. Thin wrapper over kernels._fit_scores_xp — the one
    formula the device kernels, the batch solver, and this host oracle
    all evaluate (parity pinned by test_batch_solver.py)."""
    from .kernels import fit_scores_np
    return fit_scores_np(available, used, spread_alg=False)


def _preempt_pick_host(available, used, evictable, ask, feasible, net_prio,
                       active) -> np.ndarray:
    """Numpy mirror of kernels.preempt_pick for small (nodes x requests)
    shapes — identical node ordering, no device round trip."""
    pscore = 1.0 / (1.0 + np.exp(0.0048 * (net_prio - 2048.0)))
    evictable = evictable.copy()
    picks = np.full(active.shape[0], -1, dtype=np.int32)
    neg = -1.0e30
    for i in range(active.shape[0]):
        if not active[i]:
            continue
        new_used = used + ask[None, :]
        deficit = np.maximum(new_used - available, 0.0)
        can = feasible & (deficit <= evictable).all(axis=1)
        if not can.any():
            continue
        needs_evict = (deficit > 0.0).any(axis=1)
        fitness = _binpack_fitness_np(available,
                                      np.minimum(new_used, available))
        score = np.where(
            can,
            (fitness + np.where(needs_evict, pscore, 0.0))
            / (1.0 + needs_evict.astype(float)),
            neg)
        best = int(np.argmax(score))
        if score[best] <= neg:
            continue
        picks[i] = best
        used[best] = np.minimum(used[best] + ask, available[best])
        evictable[best] = np.maximum(evictable[best] - deficit[best], 0.0)
    return picks


def _preempt_solve_host(available, used, ask, feasible, net_prio, active,
                        v_prio, v_vec, v_elig, v_flag):
    """Numpy mirror of kernels.preempt_solve — same node ordering AND the
    same priority-ascending victim-prefix rule, same op order, so the
    small-shape path and the parity tests pin the kernel bit-exact
    (victims, order, post-eviction usage). Returns (picks, victims,
    flagged, scores) with the kernel's shapes."""
    pscore = 1.0 / (1.0 + np.exp(0.0048 * (net_prio - 2048.0)))
    used = np.asarray(used, dtype=np.float64).copy()
    v_vec = np.asarray(v_vec, dtype=np.float64)
    elig = np.asarray(v_elig, dtype=bool)
    ev = (v_vec * elig[:, :, None]).sum(axis=1)
    taken = np.zeros(elig.shape, dtype=bool)
    kq, vq = active.shape[0], elig.shape[1]
    picks = np.full(kq, -1, dtype=np.int32)
    victims = np.zeros((kq, vq), dtype=bool)
    flagged = np.zeros(kq, dtype=bool)
    neg = -1.0e30
    scores = np.full(kq, neg)
    for i in range(kq):
        if not active[i]:
            continue
        new_used = used + ask[None, :]
        deficit = np.maximum(new_used - available, 0.0)
        can = feasible & (deficit <= ev).all(axis=1)
        if not can.any():
            continue
        needs_evict = (deficit > 0.0).any(axis=1)
        fitness = _binpack_fitness_np(available,
                                      np.minimum(new_used, available))
        score = np.where(
            can,
            (fitness + np.where(needs_evict, pscore, 0.0))
            / (1.0 + needs_evict.astype(float)),
            neg)
        best = int(np.argmax(score))
        if score[best] <= neg:
            continue
        row = elig[best] & ~taken[best]
        vecs = v_vec[best] * row[:, None]
        cum_before = np.cumsum(vecs, axis=0) - vecs
        def_b = deficit[best]
        sel = (row & bool(needs_evict[best])
               & ((def_b[None, :] > 0.0)
                  & (cum_before < def_b[None, :])).any(axis=1))
        evicted = (v_vec[best] * sel[:, None]).sum(axis=0)
        picks[i] = best
        victims[i] = sel
        flagged[i] = bool((sel & v_flag[best]).any())
        scores[i] = score[best]
        used[best] = np.maximum(used[best] + ask - evicted, 0.0)
        ev[best] = np.maximum(ev[best] - evicted, 0.0)
        taken[best] |= sel
    return picks, victims, flagged, scores


# Preemption-path counters: kernel_preempted = placements whose victims
# came straight from the preempt_solve column prefix; host_preempted =
# rows re-routed through the exact host scanner (flagged port/device
# holders, exact-resource groups, or a revalidation miss);
# victim_parity_checked = kernel rows revalidated host-side via
# allocs_fit before commit (every kernel row takes this check, so
# kernel_preempted counts only validated successes). Mirrored into the
# Registry as nomad.preempt.* for the obs plane; read via
# preempt_stats() (bench cfg4, chaos solve-smoke).
PREEMPT_STATS = {"kernel_preempted": 0, "host_preempted": 0,
                 "victim_parity_checked": 0}
_PREEMPT_STATS_LOCK = __import__("threading").Lock()
# shapes (n_pad, k_pad, v_pad, d) already compiled: later launches of the
# same shape run under a jit_guard no_retrace window (retrace there is a
# bug, not a warmup)
_PREEMPT_WARM: set = set()
# same discipline for the other placer-driven launch sites: per-eval
# fused solve, resident bulk solve, generic bulk solve
_FUSED_WARM: set = set()
_BULK_FUSED_WARM: set = set()
_BULK_WARM: set = set()


def _warm_launch(fn, shape_key, warm: set):
    """Shape-keyed launch window around one kernel launch; the
    implementation now lives in :func:`solver.warm_launch` (shared with
    the solver service and the incremental-state scatter), kept here as
    an alias so the placer's launch sites and tests keep their name."""
    from .solver import warm_launch

    return warm_launch(fn, shape_key, warm)


def preempt_stats() -> Dict[str, int]:
    """Snapshot of the preemption-path counters (thread-safe copy)."""
    with _PREEMPT_STATS_LOCK:
        return dict(PREEMPT_STATS)


def _count_preempt(**deltas: int) -> None:
    from ..core.metrics import REGISTRY

    with _PREEMPT_STATS_LOCK:
        for key, n in deltas.items():
            PREEMPT_STATS[key] += n
    for key, n in deltas.items():
        if n:
            REGISTRY.incr(f"nomad.preempt.{key}", n)


# Per tensor build, how many Allocation deltas hit the event stream
# since the previous build anywhere in the process — the exact row
# count the O(Δ) scatter update (tensor/incremental.py) touches instead
# of a full O(nodes) rebuild. With an incremental feed attached to the
# build's store the count is feed-native (exact: Allocation events the
# feed actually drained, resyncs included); otherwise it falls back to
# the process-wide counter diff that seeded the ROADMAP item.
_DELTA_MARK_LOCK = __import__("threading").Lock()
_DELTA_MARK = [0.0]


def _changed_allocs_since_last_build(store=None) -> int:
    from ..core.metrics import REGISTRY

    if store is not None:
        from .incremental import feed_for, incr_enabled

        feed = feed_for(store) if incr_enabled() else None
        if feed is not None:
            delta = float(feed.take_build_delta_count())
            REGISTRY.observe("nomad.worker.changed_allocs_per_build", delta)
            return int(delta)
    now = REGISTRY.get("nomad.events.alloc_deltas")
    with _DELTA_MARK_LOCK:
        prev, _DELTA_MARK[0] = _DELTA_MARK[0], now
    delta = max(0.0, now - prev)  # REGISTRY.reset between benches rewinds
    REGISTRY.observe("nomad.worker.changed_allocs_per_build", delta)
    return int(delta)


# One solve at a time across racing workers' PER-EVAL kernel path (the
# device serializes launches regardless); see the critical-section note
# in place(). The bulk path has its own serializer (the solver service).
_PER_EVAL_SOLVE_LOCK = __import__("threading").Lock()


class TPUPlacer:
    """Placer implementation: dense-tensor batch solve on the device."""

    def __init__(self, algorithm: str = enums.SCHED_ALG_BINPACK):
        # fit formula to use on the device; "tpu-binpack" keeps BestFit
        self.algorithm = algorithm

    def place(
        self,
        ctx: EvalContext,
        job: Job,
        requests: Sequence[PlacementRequest],
        nodes: Sequence[Node],
        commit,
        *,
        batch: bool = False,
        preemption_enabled: bool = False,
        attempt: int = 0,
    ) -> None:
        from .kernels import pack_solve_args, solve_task_group_fused

        if not nodes:
            from ..scheduler.reconcile import BulkPlacementRequest

            for req in requests:
                m = ctx.new_metrics()
                m.nodes_in_pool = 0
                if isinstance(req, BulkPlacementRequest):
                    fail_bulk = getattr(commit, "fail_bulk", None)
                    if fail_bulk is not None:
                        fail_bulk(req.task_group, req.count)
                        continue
                    for r in req.expand():
                        commit(r, None)
                    continue
                commit(req, None)
            return

        # Per-eval tie-break permutation, same seed discipline as the
        # host path's node shuffle (reference scheduler/util.go:167
        # shuffleNodes): scores are order-invariant, but the kernel's
        # argmax tie-breaks by priority order — without it every
        # concurrently-racing worker picks the same winners among
        # equal-scoring nodes and the plan applier rejects all but one
        # (optimistic-concurrency livelock). The permutation rides INTO
        # the kernel so the host-side node order stays canonical and the
        # per-node arrays stay cacheable across evals (ClusterStatic).
        with TRACER.span("worker.tensor_build", n=len(nodes),
                         changed_allocs=_changed_allocs_since_last_build(
                             getattr(ctx.snapshot, "_store", None))):
            cluster = ClusterTensors.build(ctx, nodes)
        nodes = cluster.nodes
        # crc32, not hash(): the seed must be deterministic ACROSS
        # processes (leader failover replaying an eval must explore the
        # same permutation), and hash() is salted per process
        seed = zlib.crc32(f"{ctx.eval_id}:{attempt}".encode())
        tie_perm = np.random.default_rng(seed).permutation(
            cluster.n_pad).astype(np.int32)

        # group requests per task group, preserving intra-group order
        groups: Dict[str, List[PlacementRequest]] = {}
        order: List[str] = []
        for req in requests:
            name = req.task_group.name
            if name not in groups:
                groups[name] = []
                order.append(name)
            groups[name].append(req)

        for gi, name in enumerate(order):
            reqs = groups[name]
            tg = reqs[0].task_group
            if gi > 0:  # build() already computed usage for the first group
                cluster.refresh_usage(ctx)

            from ..scheduler.reconcile import BulkPlacementRequest

            if len(reqs) == 1 and isinstance(reqs[0], BulkPlacementRequest):
                # columnar fast path: K fresh placements as ONE request
                # committed as ONE AllocBlock (the reconciler only emits
                # this shape when nothing per-alloc is pending)
                bulk = reqs[0]
                tgt = build_task_group_tensors(ctx, job, tg, cluster,
                                               algorithm=self.algorithm)
                if (self._bulk_shape_ok(ctx, tg, tgt)
                        and getattr(commit, "commit_block", None) is not None):
                    with TRACER.span("worker.solve_bulk", k=bulk.count,
                                     columnar=True):
                        self._place_bulk_columnar(
                            ctx, job, tg, bulk, cluster, tgt, commit, seed,
                            sched_batch=batch,
                            preemption_enabled=preemption_enabled,
                            attempt=attempt)
                    continue
                # group features (spread/ports/devices/...) need the
                # per-placement machinery: expand and fall through
                # (reusing the tensors just built)
                reqs = bulk.expand()
                prebuilt_tgt = tgt
            else:
                prebuilt_tgt = None

            if len(reqs) <= self.HOST_CUTOVER:
                # tiny groups (mostly partial-commit remainders): a
                # device launch costs ~100ms of tunnel latency while the
                # host oracle scores the same nodes in a few ms per
                # placement — same math, parity-tested
                for req in reqs:
                    option = self._host_one(ctx, job, tg, nodes, req,
                                            batch, preemption_enabled,
                                            attempt)
                    commit(req, option)
                continue

            tgt = (prebuilt_tgt if prebuilt_tgt is not None
                   else build_task_group_tensors(ctx, job, tg, cluster,
                                                 algorithm=self.algorithm))

            if self._bulk_eligible(ctx, tg, reqs, tgt):
                with TRACER.span("worker.solve_bulk", k=len(reqs),
                                 columnar=False):
                    self._place_bulk(ctx, job, tg, reqs, cluster, tgt,
                                     commit, tie_perm, seed,
                                     sched_batch=batch,
                                     preemption_enabled=preemption_enabled,
                                     attempt=attempt)
                continue

            k = len(reqs)
            k_pad = _pad_pow2(k, floor=1)
            penalty_idx = np.full(k_pad, -1, dtype=np.int32)
            active = np.zeros(k_pad, dtype=bool)
            active[:k] = True
            for i, req in enumerate(reqs):
                if req.ignore_node:
                    penalty_idx[i] = cluster.node_index.get(req.ignore_node, -1)

            # The usage gather -> solve -> in-flight registration runs
            # as ONE critical section across racing workers: the device
            # serializes launches anyway, and without this ordering two
            # concurrent evals both fill the same near-full best-fit
            # nodes to the brim and the applier rejects the loser's
            # whole node lists (the round-4 spread-rung rejection gap —
            # measured: overflows on the smallest-capacity nodes, base +
            # planned > available). Inside the lock each solve re-reads
            # usage WITH every earlier solve's overlay entries folded
            # (tensor/overlay.py), so racing workers interleave around
            # each other like the bulk path's carry provides for free.
            from .overlay import INFLIGHT

            # the span covers the lock wait too: serialization behind
            # racing workers is exactly the stall the trace should show
            with TRACER.span("worker.solve", k=len(reqs)), \
                    _PER_EVAL_SOLVE_LOCK:
                cluster.refresh_usage(ctx)
                # device/core count columns extend the dense dims
                has_extra = tgt.extra_ask is not None and len(tgt.extra_ask)
                if has_extra:
                    avail = np.concatenate([cluster.available, tgt.extra_cap],
                                           axis=1)
                    used = np.concatenate([cluster.used, tgt.extra_used],
                                          axis=1)
                    ask = np.concatenate([tgt.ask, tgt.extra_ask])
                else:
                    avail, used, ask = (cluster.available, cluster.used,
                                        tgt.ask)

                packed = pack_solve_args(
                    avail, used, tgt.placed_tg, tgt.placed_job,
                    ask, tgt.feasible, tgt.affinity_boost, penalty_idx,
                    active,
                    tgt.spread_val_id, tgt.spread_val_ok, tgt.spread_counts,
                    tgt.spread_desired, tgt.spread_has_targets,
                    tgt.spread_weight,
                    -1.0, tgt.tg_count, tgt.dh_job, tgt.dh_tg, tgt.spread_alg,
                    dev_affinity=tgt.dev_affinity,
                    dp_val_id=tgt.dp_val_id, dp_val_ok=tgt.dp_val_ok,
                    dp_counts0=tgt.dp_counts, dp_limit=tgt.dp_limit,
                    tie_perm=tie_perm)
                import jax

                # explicit shipment + shape-keyed window; the
                # device_get is the launch's only host sync
                dev = jax.device_put(packed)
                fused_key = tuple(np.shape(a) for a in packed)
                with _warm_launch(solve_task_group_fused, fused_key,
                                  _FUSED_WARM):
                    out = jax.device_get(solve_task_group_fused(*dev))
                choices = out[0].astype(np.int64)
                founds = out[1] > 0.5
                scores = out[2]
                if ctx.plan is not None and founds.any():
                    vec = ctx.tg_vec(tg)
                    kernel_counts: Dict[int, int] = {}
                    for i in range(len(reqs)):
                        if founds[i]:
                            ni = int(choices[i])
                            kernel_counts[ni] = kernel_counts.get(ni, 0) + 1
                    INFLIGHT.register(
                        {cluster.nodes[ni].id: vec * c
                         for ni, c in kernel_counts.items()},
                        ctx.plan)

            # exact port numbers / device instances / core ids are
            # host-side, per chosen node, after the solve (the kernel only
            # fit-checked the counts); per-node indexes carry assignments
            # across this group's placements so they don't double-book
            ask_res = ctx.tg_resources(tg)
            wants_ports = bool(ask_res.reserved_port_asks()
                               or ask_res.dynamic_port_count())
            wants_devices = bool(ask_res.devices)
            wants_cores = bool(ask_res.cores)
            numa_pol = "none"
            if wants_cores:
                from ..scheduler.devices import combined_numa_affinity

                numa_pol = combined_numa_affinity(tg)
            net_idx: Dict[int, object] = {}
            dev_idx: Dict[int, object] = {}
            core_used: Dict[int, set] = {}

            n_feasible = int(tgt.feasible[: len(nodes)].sum())
            preempt_queue: List[PlacementRequest] = []
            for i, req in enumerate(reqs):
                metrics = ctx.new_metrics()
                metrics.nodes_in_pool = len(nodes)
                metrics.nodes_evaluated = len(nodes)
                if founds[i]:
                    ni = int(choices[i])
                    node = cluster.nodes[ni]
                    option = RankedNode(node=node)
                    option.final_score = float(scores[i])
                    option.score_meta["normalized-score"] = option.final_score
                    metrics.scores[f"{node.id}.normalized-score"] = option.final_score
                    if wants_ports:
                        from ..structs.network import NetworkIndex

                        idx = net_idx.get(ni)
                        if idx is None:
                            idx = net_idx[ni] = NetworkIndex(node)
                            idx.add_allocs(ctx.proposed_allocs(node.id))
                        ports, err = idx.assign_ports(ask_res)
                        if err:
                            metrics.exhaust_node("ports")
                            commit(req, None)
                            continue
                        option.allocated_ports = ports
                    if wants_devices or wants_cores:
                        ok = self._assign_ids(ctx, ask_res, numa_pol, ni, node,
                                              option, dev_idx, core_used)
                        if not ok:
                            # count-fit admitted a node the exact id
                            # assignment can't satisfy (NUMA require /
                            # overlapping asks): host selector for this
                            # request alone
                            option = self._host_one(ctx, job, tg, nodes, req,
                                                    batch, preemption_enabled,
                                                    attempt)
                            commit(req, option)
                            if option is not None:
                                # the fallback assigned ids on its own
                                # node; drop that node's caches so later
                                # kernel placements rebuild them from the
                                # committed plan instead of double-booking
                                self._invalidate_node(
                                    cluster, option.node.id,
                                    net_idx, dev_idx, core_used)
                            continue
                    commit(req, option)
                    continue
                if preemption_enabled:
                    preempt_queue.append(req)
                    continue
                self._attribute_failure(ctx, metrics, len(nodes), n_feasible)
                commit(req, None)
            if preempt_queue:
                self._preempt_batch(
                    ctx, job, tg, preempt_queue, cluster, tgt, commit,
                    sched_batch=batch, attempt=attempt,
                    n_feasible=n_feasible,
                    invalidate=lambda nid: self._invalidate_node(
                        cluster, nid, net_idx, dev_idx, core_used))

    # -- bulk (count-based) solve: the C2M path --

    BULK_MIN = 256     # below this the per-placement scan is fine
    BULK_STEP = 256    # placements assigned per scan step
    HOST_CUTOVER = 16  # at/below this the host oracle beats a launch
    # preempt_solve runs on-device only when the (nodes x requests)
    # matrix is big enough to beat the tunnel's fixed latency (measured
    # at 1024x512/V=8: warm scan ~13 ms vs ~80 ms for the numpy mirror)
    PREEMPT_DEVICE_MIN = 1 << 18

    def _bulk_eligible(self, ctx, tg, reqs, tgt) -> bool:
        """K large, every request a fresh placement, BestFit binpack with
        no spread/distinct-hosts semantics (fill-to-capacity is only the
        exact greedy trajectory for BestFit: the winner keeps winning
        until full; WorstFit/spread round-robin per placement, which a
        batched step would mis-place — measured, not guessed), and
        nothing that needs per-alloc host-side id assignment (exact
        ports, device instances, cores) or distinct_property tables."""
        if len(reqs) < self.BULK_MIN:
            return False
        if tgt.spread_alg or tgt.dh_job or tgt.dh_tg:
            return False
        if tgt.spread_val_id.shape[0]:
            return False
        if tgt.extra_ask is not None and len(tgt.extra_ask):
            return False
        if tgt.dp_val_id is not None and tgt.dp_val_id.shape[0]:
            return False
        ask_res = ctx.tg_resources(tg)
        if ask_res.reserved_port_asks() or ask_res.dynamic_port_count():
            return False
        return all(req.previous_alloc is None and not req.ignore_node
                   and not req.canary for req in reqs)

    def _bulk_shape_ok(self, ctx, tg, tgt) -> bool:
        """Task-group-level bulk eligibility (the per-request conditions
        of _bulk_eligible hold for a BulkPlacementRequest by
        construction)."""
        if tgt.spread_alg or tgt.dh_job or tgt.dh_tg:
            return False
        if tgt.spread_val_id.shape[0]:
            return False
        if tgt.extra_ask is not None and len(tgt.extra_ask):
            return False
        if tgt.dp_val_id is not None and tgt.dp_val_id.shape[0]:
            return False
        ask_res = ctx.tg_resources(tg)
        if ask_res.reserved_port_asks() or ask_res.dynamic_port_count():
            return False
        return True

    def _solve_bulk_counts(self, ctx, cluster, tgt, k: int, seed,
                           tie_perm) -> np.ndarray:
        """Run the count-based bulk solve through whichever backend fits
        (solver service with device-resident carry > fused resident
        arrays > generic kernel) -> (N_pad,) int64 per-node counts."""
        from .kernels import solve_bulk, solve_bulk_fused
        from .solver import BulkSolverService

        k_pad = _pad_pow2(k, floor=self.BULK_STEP)
        n_steps = k_pad // self.BULK_STEP
        static = cluster.static
        if (static is not None and tgt.feas_base is not None
                and k <= BulkSolverService.MAX_K):
            # The service path serializes ALL bulk solves — including
            # partial-commit retries (placed_tg/placed_job nonzero) —
            # on one device-resident carry, so racing workers can never
            # double-book. Retries routed around the service (the pre-r5
            # gate) solved against store-latest usage and collided with
            # each other, compounding the rejection cascade at 2M scale.
            # Cost: the carry solve drops the per-node anti-affinity
            # term for the retried remainder (a score preference, not a
            # capacity constraint; fresh solves have placed_* == 0).
            from .incremental import device_used_fn
            from .solver import get_service

            service = get_service()
            counts, solve_token = service.solve(
                static=static, feas_base=tgt.feas_base,
                aff=tgt.affinity_boost, ask=tgt.ask, k=k,
                tg_count=tgt.tg_count, seed=seed,
                used_fn=cluster.latest_usage,
                used_dev_fn=device_used_fn(cluster._store, static),
                joint=(self.algorithm == enums.SCHED_ALG_TPU_SOLVE))
            if ctx.plan is not None:
                ctx.plan.post_apply_hooks.append(
                    lambda result, _t=solve_token: service.confirm(
                        _t, getattr(result, "rejected_nodes", None) or ()))
            return counts
        if static is not None and tgt.feas_base is not None:
            from .solver import ensure_resident

            f32 = np.float32
            avail_dev, feas_dev, aff_dev = ensure_resident(
                static, tgt.feas_base, tgt.affinity_boost)
            import jax

            dyn = np.concatenate(
                [cluster.used, tgt.placed_tg[:, None],
                 tgt.placed_job[:, None]], axis=1).astype(f32)
            # avail/feas/aff are already device-resident (device_put of
            # a committed array is a no-op); ship the per-solve host
            # args explicitly — scalars included, an implicit scalar
            # transfer trips the warm window's transfer guard
            host = jax.device_put((dyn, tgt.ask.astype(f32), np.int32(k),
                                   f32(tgt.tg_count), np.uint32(seed)))
            fused_key = (dyn.shape, tgt.ask.shape,
                         np.shape(avail_dev), n_steps)
            with _warm_launch(solve_bulk_fused, fused_key,
                              _BULK_FUSED_WARM):
                out = jax.device_get(solve_bulk_fused(
                    avail_dev, feas_dev, aff_dev, *host,
                    batch=self.BULK_STEP, n_steps=n_steps))
            return out.astype(np.int64)
        import jax

        args = (cluster.available, cluster.used, tgt.ask, tgt.feasible,
                tgt.placed_tg, tgt.placed_job, tgt.affinity_boost,
                np.zeros(cluster.n_pad), tgt.spread_val_id,
                tgt.spread_val_ok, tgt.spread_counts, tgt.spread_desired,
                tgt.spread_has_targets, tgt.spread_weight, np.int32(k),
                tgt.tg_count, tgt.dh_job, tgt.dh_tg, tgt.spread_alg,
                tie_perm)
        dev = jax.device_put(args)
        bulk_key = tuple(np.shape(a) for a in args) + (n_steps,)
        with _warm_launch(solve_bulk, bulk_key, _BULK_WARM):
            out = jax.device_get(solve_bulk(
                *dev, batch=self.BULK_STEP, n_steps=n_steps))
        return out.astype(np.int64)

    def _place_bulk_columnar(self, ctx, job, tg, bulk, cluster, tgt,
                             commit, seed, *, sched_batch: bool,
                             preemption_enabled: bool, attempt: int) -> None:
        """The C2M commit shape: one solve -> one AllocBlock. Host work
        is O(touched nodes), not O(K) — per-alloc ids/names materialize
        lazily from the block (structs/alloc.py AllocBlock)."""
        k = bulk.count
        tie_perm = None  # only the generic kernel consumes it
        if cluster.static is None or tgt.feas_base is None:
            tie_perm = np.random.default_rng(seed).permutation(
                cluster.n_pad).astype(np.int32)
        counts = self._solve_bulk_counts(ctx, cluster, tgt, k, seed, tie_perm)
        # everything below is pure host work on fetched counts — under a
        # pipelined solver this "apply" window runs WHILE the device
        # solves the next batch; the span makes that overlap visible
        # next to solver.shard/solver.launch in the trace
        with TRACER.span("solver.apply", k=k):
            mean_score = self._bulk_trajectory_mean(counts, cluster, tgt)

            metrics = ctx.new_metrics()
            metrics.nodes_in_pool = len(cluster.nodes)
            metrics.nodes_evaluated = len(cluster.nodes)
            metrics.scores["bulk.normalized-score"] = mean_score

            nz = np.nonzero(counts)[0]
            placed_counts = counts[nz]
            total = int(placed_counts.sum())
            nodes = cluster.nodes
            commit.commit_block(
                tg,
                [nodes[int(ni)].id for ni in nz],
                [nodes[int(ni)].name for ni in nz],
                placed_counts.astype(np.int64),
                np.asarray(bulk.name_indices[:total], dtype=np.int64),
                mean_score)

        n_unplaced = k - total
        if not n_unplaced:
            return
        n_feasible = int(tgt.feasible[: len(nodes)].sum())
        if preemption_enabled:
            # rare tail: expand ONLY the remainder for the per-request
            # preemption machinery
            from ..scheduler.reconcile import BulkPlacementRequest

            remainder = BulkPlacementRequest(
                task_group=tg, job_id=bulk.job_id,
                name_indices=bulk.name_indices[total:]).expand()
            self._preempt_batch(ctx, job, tg, remainder, cluster, tgt,
                                commit, sched_batch=sched_batch,
                                attempt=attempt, n_feasible=n_feasible)
            return
        self._attribute_failure(ctx, metrics, len(nodes), n_feasible)
        commit.fail_bulk(tg, n_unplaced)

    def _place_bulk(self, ctx, job, tg, reqs, cluster, tgt, commit,
                    tie_perm, seed, *, sched_batch: bool,
                    preemption_enabled: bool, attempt: int) -> None:
        """Place K identical requests as per-node COUNTS from one
        solve_bulk launch (one readback regardless of K), then commit
        through the scheduler's normal commit callback so plan assembly
        stays authoritative. With a cached ClusterStatic the fused entry
        runs against device-resident capacity/mask/affinity arrays and
        ships only the (N, D+2) dynamic matrix + scalars per eval."""
        k = len(reqs)
        counts = self._solve_bulk_counts(ctx, cluster, tgt, k, seed, tie_perm)
        mean_score = self._bulk_trajectory_mean(counts, cluster, tgt)

        # one shared metrics object for the whole group: per-alloc
        # AllocMetric at bulk scale is pure overhead (the mean normalized
        # score is what the benches and the eval summary consume)
        metrics = ctx.new_metrics()
        metrics.nodes_in_pool = len(cluster.nodes)
        metrics.nodes_evaluated = len(cluster.nodes)
        metrics.scores["bulk.normalized-score"] = mean_score

        commit_many = getattr(commit, "commit_many", None)
        pos = 0
        if commit_many is not None:
            for ni in np.nonzero(counts)[0]:
                c = int(counts[ni])
                commit_many(tg, cluster.nodes[ni], reqs[pos:pos + c],
                            mean_score)
                pos += c
        else:
            for ni in np.nonzero(counts)[0]:
                node = cluster.nodes[ni]
                for _ in range(int(counts[ni])):
                    req = reqs[pos]
                    pos += 1
                    option = RankedNode(node=node)
                    option.final_score = mean_score
                    commit(req, option)
        unplaced = reqs[pos:]
        if not unplaced:
            return
        n_feasible = int(tgt.feasible[: len(cluster.nodes)].sum())
        if preemption_enabled:
            self._preempt_batch(ctx, job, tg, unplaced, cluster, tgt,
                                commit, sched_batch=sched_batch,
                                attempt=attempt, n_feasible=n_feasible)
            return
        for req in unplaced:
            metrics = ctx.new_metrics()
            metrics.nodes_in_pool = len(cluster.nodes)
            metrics.nodes_evaluated = len(cluster.nodes)
            self._attribute_failure(ctx, metrics, len(cluster.nodes),
                                    n_feasible)
            commit(req, None)

    # -- batched preemption: kernel node choice + host victim selection --

    def _preempt_batch(self, ctx, job, tg, reqs, cluster, tgt, commit, *,
                       sched_batch: bool, attempt: int, n_feasible: int,
                       invalidate=None) -> None:
        """Preemption for K unplaced requests as ONE in-kernel solve:
        kernels.preempt_solve picks each request's node (fit after
        eviction + the logistic preemption penalty) AND its concrete
        victims (priority-ascending prefix over the node's eligible
        victim column, carry-committed so siblings never double-claim).
        The host's remaining work per kernel row is one allocs_fit
        revalidation of the selected victim set (counted as
        victim_parity_checked) before the RankedNode commits.

        Span layout follows the work's new home: building the victim
        columns is tensor build (`worker.tensor_build`), the device/
        mirror launch is solver work (`solver.preempt`), revalidate +
        commit of kernel rows is `worker.preempt_commit`, and
        `worker.preempt` — the historically GC-noisy pure-Python host
        pass PERF.md tracks — now wraps ONLY the exact-scanner arm, so
        it reads ~0 when the kernel resolves every row."""
        from .cluster import build_victim_tensors

        with TRACER.span("worker.tensor_build", kind="victim_columns"):
            vt = build_victim_tensors(ctx, cluster, job.priority)
        k_pad = _pad_pow2(len(reqs), floor=1)
        active = np.zeros(k_pad, dtype=bool)
        active[: len(reqs)] = True
        with TRACER.span("solver.preempt", k=len(reqs)):
            picks, victims, flagged, scores = self._launch_preempt_solve(
                cluster, tgt, vt, active, k_pad)
        with TRACER.span("worker.preempt_commit", k=len(reqs)):
            self._preempt_batch_inner(
                ctx, job, tg, reqs, cluster, tgt, commit, vt,
                picks, victims, flagged, scores,
                sched_batch=sched_batch, attempt=attempt,
                n_feasible=n_feasible, invalidate=invalidate)

    def _preempt_batch_inner(self, ctx, job, tg, reqs, cluster, tgt,
                             commit, vt, picks, victims, flagged, scores,
                             *, sched_batch: bool, attempt: int,
                             n_feasible: int, invalidate=None) -> None:
        """Resolve the kernel's (pick, victim-set) rows into committed
        placements. The exact host scanner (NodeScorer.rank ->
        preempt_for_* + filterSuperset) survives as the fallback arm:
        rows the kernel flags (victim holds exact ports/devices), groups
        that need exact id assignment, reschedules carrying a node
        penalty, and revalidation misses. Those count as host_preempted
        — ~0 on the bulk path."""
        from ..scheduler.rank import NodeScorer
        from ..structs import allocs_fit
        from ..structs.alloc import Allocation

        nodes = cluster.nodes
        ask_res = ctx.tg_resources(tg)
        # exact port numbers / device instances / cores can't come from
        # the dense victim columns — those groups keep the host scanner
        exact_needed = bool(ask_res.reserved_port_asks()
                            or ask_res.dynamic_port_count()
                            or ask_res.devices or ask_res.cores)
        ask_vec = ctx.tg_vec(tg)

        scorer = NodeScorer(ctx, job, tg, algorithm=self._host_algorithm(),
                            preemption_enabled=True)
        # one shared metrics object for kernel rows (bulk-path idiom —
        # a per-alloc AllocMetric at K=512 is pure overhead); host-arm
        # rows keep per-row metrics the scorer populates
        kernel_metrics = ctx.new_metrics()
        kernel_metrics.nodes_in_pool = len(nodes)
        kernel_metrics.nodes_evaluated = len(nodes)
        # ProposedAllocs walks snapshot + plan rows per call; cache it
        # per node and drop the entry whenever a commit mutates that
        # node's plan, so repeat rows reuse the walk without ever
        # reading a stale victim list
        prop_cache: Dict[str, list] = {}

        def proposed(node_id: str):
            out = prop_cache.get(node_id)
            if out is None:
                out = prop_cache[node_id] = ctx.proposed_allocs(node_id)
            return out

        def host_metrics():
            m = ctx.new_metrics()
            m.nodes_in_pool = len(nodes)
            m.nodes_evaluated = len(nodes)
            return m

        n_kernel = n_host = n_parity = 0
        for i, req in enumerate(reqs):
            option = None
            kernel_row = False
            ni = int(picks[i])
            if req.ignore_node:
                # rescheduled alloc: the batched pick carries no
                # node-reschedule penalty, so keep the full host scan
                # (which weighs it) for these rare requests
                ni = -1
            if 0 <= ni < len(nodes):
                node = nodes[ni]
                if not exact_needed and not bool(flagged[i]):
                    ctx.metrics = kernel_metrics
                    option = self._commit_kernel_victims(
                        ctx, node, vt, ni, victims[i], float(scores[i]),
                        ask_vec, proposed, allocs_fit, Allocation)
                    n_parity += 1
                    kernel_row = option is not None
                if option is None:
                    # exact-resource group, flagged victim, or a
                    # revalidation miss: exact victim selection + scoring
                    # on the chosen node (ports/devices/spread handled by
                    # the scorer)
                    with TRACER.span("worker.preempt"):
                        host_metrics()
                        option = scorer.rank(node)
            if option is None and not kernel_row:
                # aggregate misprediction: full host scan for this one
                with TRACER.span("worker.preempt"):
                    host_metrics()
                    option = self._preempt_fallback(ctx, job, tg, nodes,
                                                    req, sched_batch,
                                                    attempt)
            if option is not None:
                commit(req, option)
                prop_cache.pop(option.node.id, None)
                scorer.record_placement(option.node)
                if invalidate is not None:
                    invalidate(option.node.id)
                if kernel_row:
                    n_kernel += 1
                else:
                    n_host += 1
                continue
            self._attribute_failure(ctx, ctx.metrics or host_metrics(),
                                    len(nodes), n_feasible)
            commit(req, None)
        _count_preempt(kernel_preempted=n_kernel, host_preempted=n_host,
                       victim_parity_checked=n_parity)

    def _launch_preempt_solve(self, cluster, tgt, vt, active, k_pad):
        """Run kernels.preempt_solve on-device (big shapes, under a
        jit_guard no_retrace window once the shape is warm) or through
        the numpy mirror (below PREEMPT_DEVICE_MIN the tunnel's fixed
        latency dwarfs the vector work). Both arms return identical
        (picks, victims, flagged, scores) host arrays."""
        n_pad = cluster.n_pad
        if n_pad * k_pad < self.PREEMPT_DEVICE_MIN:
            return _preempt_solve_host(
                cluster.available, cluster.used.copy(), tgt.ask,
                tgt.feasible, vt.net_prio, active,
                vt.prio, vt.vec, vt.elig, vt.flagged)
        import jax

        from .kernels import preempt_solve

        f32 = np.float32
        args = (cluster.available.astype(f32), cluster.used.astype(f32),
                tgt.ask.astype(f32), tgt.feasible,
                vt.net_prio.astype(f32), active,
                vt.prio, vt.vec, vt.elig, vt.flagged)
        shape_key = (n_pad, k_pad, vt.v_pad, cluster.available.shape[1])
        # explicit shipment on BOTH arms: committed jax.Arrays and bare
        # numpy hit different jit cache entries, so a cold bare call
        # followed by a warm device_put call would read as a retrace
        dev = jax.device_put(args)
        with _warm_launch(preempt_solve, shape_key, _PREEMPT_WARM):
            out = jax.device_get(preempt_solve(*dev))
        picks, victims, flagged, scores = out
        return (np.asarray(picks), np.asarray(victims),
                np.asarray(flagged), np.asarray(scores))

    def _commit_kernel_victims(self, ctx, node, vt, ni, sel, score,
                               ask_vec, proposed, allocs_fit, Allocation):
        """Turn one kernel row (node ni + victim column mask) into a
        scored RankedNode, revalidating the post-eviction fit host-side
        with the exact AllocsFit (cores/ports collision semantics the
        dense columns can't see). Returns None on a revalidation miss —
        the caller re-routes that row through the exact scanner.

        The kernel's combined score is reused as the final score: its
        (fitness + preemption)/2 is the same mean the host scorer's
        binpack+preemption normalize() produces, evaluated against the
        solve's own carried usage — recomputing it per row was a third
        of the residual loop."""
        refs = vt.refs[ni] if ni < len(vt.refs) else []
        chosen = [refs[v] for v in np.nonzero(sel)[0] if v < len(refs)]
        prop = proposed(node.id)
        prop_ids = {a.id for a in prop}
        # a victim already evicted by an earlier host-arm row in this
        # batch is gone from proposed — its capacity is already free
        chosen = [a for a in chosen if a.id in prop_ids]
        victim_ids = {a.id for a in chosen}
        placement = Allocation(id="_cand", allocated_vec=ask_vec)
        remaining = [a for a in prop if a.id not in victim_ids]
        fit, _dim, _used_after = allocs_fit(node, remaining + [placement])
        if not fit:
            return None
        option = RankedNode(node=node)
        option.preempted_allocs = chosen or None
        option.final_score = score
        return option

    @staticmethod
    def _bulk_trajectory_mean(counts: np.ndarray, cluster, tgt) -> float:
        """Exact mean normalized score over the greedy trajectory the
        bulk counts correspond to, computed host-side (the kernel scores
        a whole step at its start, which under-reports BestFit's rising
        fill scores). No spread/dp terms by bulk eligibility; mirrors
        kernels.score_nodes for the fit + anti-affinity + node-affinity
        sub-scores (reference funcs.go:236 ScoreFitBinPack,
        rank.go:596,710,800)."""
        nz = np.nonzero(counts)[0]
        if not len(nz):
            return 0.0
        c = counts[nz]
        total = int(c.sum())
        idx = np.repeat(nz, c)
        starts = np.concatenate([[0], np.cumsum(c)[:-1]])
        t = np.arange(total) - np.repeat(starts, c) + 1.0  # 1..c per node
        ask = np.asarray(tgt.ask, dtype=np.float64)
        avail = cluster.available[idx]
        used = cluster.used[idx] + t[:, None] * ask[None, :]
        fit = _binpack_fitness_np(avail, used)
        ptg_before = tgt.placed_tg[idx] + t - 1.0
        anti_present = ptg_before > 0
        anti = -(ptg_before + 1.0) / max(tgt.tg_count, 1.0)
        aff = tgt.affinity_boost[idx]
        aff_present = aff != 0.0
        dev = tgt.dev_affinity[idx] if tgt.dev_affinity is not None else 0.0
        dev_present = dev != 0.0 if tgt.dev_affinity is not None else False
        div = (1.0 + anti_present.astype(float) + aff_present.astype(float)
               + np.asarray(dev_present, dtype=float))
        score = (fit + np.where(anti_present, anti, 0.0) + aff
                 + np.where(dev_present, dev, 0.0)) / div
        return float(score.mean())

    @staticmethod
    def _attribute_failure(ctx, metrics, n_nodes: int, n_feasible: int) -> None:
        """Failure attribution the way the host path would do it: nodes
        masked by constraints/drivers are "filtered", nodes that passed
        feasibility but didn't fit are "exhausted" (reference feasible.go
        filter vs rank.go exhaust metrics)."""
        masked = n_nodes - n_feasible
        if masked:
            metrics.nodes_filtered += masked
            metrics.constraint_filtered["task group constraints"] = (
                metrics.constraint_filtered.get("task group constraints", 0)
                + masked)
        if n_feasible > 0:
            metrics.exhaust_node("resources")

    def _assign_ids(self, ctx, ask_res, numa_pol: str, ni: int, node,
                    option: RankedNode, dev_idx: Dict[int, object],
                    core_used: Dict[int, set]) -> bool:
        """Post-solve concrete id assignment for one placement on the
        chosen node. Per-node indexes live for the group's whole pass so
        sibling placements never double-book. A False return leaves any
        staged device instances reserved — conservative, and only
        reachable on count-fit mispredictions."""
        from ..scheduler.devices import DeviceIndex, select_cores, used_cores

        proposed = None
        if ask_res.devices:
            idx = dev_idx.get(ni)
            if idx is None:
                proposed = ctx.proposed_allocs(node.id)
                idx = dev_idx[ni] = DeviceIndex(node, proposed)
            assignment = idx.assign(ask_res.devices, ctx.regex_cache,
                                    ctx.version_cache)
            if assignment is None:
                return False
            option.allocated_devices = assignment
        if ask_res.cores:
            taken = core_used.get(ni)
            if taken is None:
                if proposed is None:
                    proposed = ctx.proposed_allocs(node.id)
                taken = core_used[ni] = used_cores(proposed)
            cores = select_cores(node, (), int(ask_res.cores), numa_pol,
                                 taken=taken)
            if cores is None:
                return False
            taken.update(cores)
            option.allocated_cores = cores
        return True

    @staticmethod
    def _invalidate_node(cluster, node_id: str, *caches: Dict[int, object]) -> None:
        ni = cluster.node_index.get(node_id)
        if ni is not None:
            for cache in caches:
                cache.pop(ni, None)

    def _host_algorithm(self) -> str:
        return (enums.SCHED_ALG_BINPACK
                if self.algorithm in (enums.SCHED_ALG_TPU_BINPACK,
                                      enums.SCHED_ALG_TPU_SOLVE)
                else self.algorithm)

    def _host_one(self, ctx, job, tg, nodes, req, batch: bool,
                  preemption_enabled: bool, attempt: int) -> Optional[RankedNode]:
        penalty = frozenset({req.ignore_node}) if req.ignore_node else frozenset()
        return select_best_node(
            ctx, job, tg, nodes,
            batch=batch,
            algorithm=self._host_algorithm(),
            preemption_enabled=preemption_enabled,
            penalty_nodes=penalty,
            attempt=attempt,
        )

    def _preempt_fallback(self, ctx, job, tg, nodes, req, batch: bool,
                          attempt: int) -> Optional[RankedNode]:
        return self._host_one(ctx, job, tg, nodes, req, batch,
                              preemption_enabled=True, attempt=attempt)
