"""TPUPlacer: batched placement behind SchedulerAlgorithm="tpu-binpack"
(the new algorithm value plugging into the reference's enum,
nomad/structs/operator.go:199-255).

Lowering strategy per evaluation:
  1. one ClusterTensors build (nodes + proposed usage),
  2. per task group: host-precompiled feasibility/affinity/spread arrays,
  3. one jitted solve_task_group scan placing all of the group's
     requests with full cross-placement visibility,
  4. commits mapped back through the scheduler's commit callback so the
     plan object and ctx.proposed_allocs stay authoritative.

Preemption stays host-side: when the kernel finds no fit and preemption
is enabled, the per-request fallback runs the host NodeScorer preemption
path (reference rank.go:205-587's preemption fallback arm). Task groups
asking for devices or reserved cores also fall back — their per-instance
fit logic lands with the device kernels.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..structs import Job, Node, enums
from ..scheduler.context import EvalContext
from ..scheduler.feasible import distinct_property_constraints
from ..scheduler.rank import NodeScorer, RankedNode, select_best_node
from ..scheduler.reconcile import PlacementRequest
from .cluster import ClusterTensors, build_task_group_tensors, _pad_pow2


def _needs_host_path(job: Job, tg) -> bool:
    if any(t.resources.devices for t in tg.tasks):
        return True
    if any(t.resources.cores for t in tg.tasks):
        return True
    if distinct_property_constraints(job, tg):
        return True
    return False


class TPUPlacer:
    """Placer implementation: dense-tensor batch solve on the device."""

    def __init__(self, algorithm: str = enums.SCHED_ALG_BINPACK):
        # fit formula to use on the device; "tpu-binpack" keeps BestFit
        self.algorithm = algorithm

    def place(
        self,
        ctx: EvalContext,
        job: Job,
        requests: Sequence[PlacementRequest],
        nodes: Sequence[Node],
        commit,
        *,
        batch: bool = False,
        preemption_enabled: bool = False,
        attempt: int = 0,
    ) -> None:
        from .kernels import pack_solve_args, solve_task_group_fused

        if not nodes:
            for req in requests:
                m = ctx.new_metrics()
                m.nodes_in_pool = 0
                commit(req, None)
            return

        # Per-eval node shuffle, same seed discipline as the host path
        # (reference scheduler/util.go:167 shuffleNodes): scores are
        # order-invariant, but the kernel's argmax tie-breaks by index —
        # without the shuffle every concurrently-racing worker picks the
        # same winners among equal-scoring nodes and the plan applier
        # rejects all but one (optimistic-concurrency livelock).
        nodes = ctx.shuffled_nodes(list(nodes), attempt)
        cluster = ClusterTensors.build(ctx, nodes)

        # group requests per task group, preserving intra-group order
        groups: Dict[str, List[PlacementRequest]] = {}
        order: List[str] = []
        for req in requests:
            name = req.task_group.name
            if name not in groups:
                groups[name] = []
                order.append(name)
            groups[name].append(req)

        host_fallback = None
        for gi, name in enumerate(order):
            reqs = groups[name]
            tg = reqs[0].task_group
            if gi > 0:  # build() already computed usage for the first group
                cluster.refresh_usage(ctx)

            if _needs_host_path(job, tg):
                if host_fallback is None:
                    from ..scheduler.placer import HostPlacer

                    host_fallback = HostPlacer(algorithm=self.algorithm)
                host_fallback.place(ctx, job, reqs, nodes, commit,
                                    batch=batch,
                                    preemption_enabled=preemption_enabled,
                                    attempt=attempt)
                continue

            tgt = build_task_group_tensors(ctx, job, tg, cluster,
                                           algorithm=self.algorithm)

            k = len(reqs)
            k_pad = _pad_pow2(k, floor=1)
            penalty_idx = np.full(k_pad, -1, dtype=np.int32)
            active = np.zeros(k_pad, dtype=bool)
            active[:k] = True
            for i, req in enumerate(reqs):
                if req.ignore_node:
                    penalty_idx[i] = cluster.node_index.get(req.ignore_node, -1)

            packed = pack_solve_args(
                cluster.available, cluster.used, tgt.placed_tg, tgt.placed_job,
                tgt.ask, tgt.feasible, tgt.affinity_boost, penalty_idx, active,
                tgt.spread_val_id, tgt.spread_val_ok, tgt.spread_counts,
                tgt.spread_desired, tgt.spread_has_targets, tgt.spread_weight,
                -1.0, tgt.tg_count, tgt.dh_job, tgt.dh_tg, tgt.spread_alg)
            out = np.asarray(solve_task_group_fused(*packed))  # one readback
            choices = out[0].astype(np.int64)
            founds = out[1] > 0.5
            scores = out[2]

            # exact port numbers are host-side, per node, after the solve
            # (the kernel only fit-checked the counts); one NetworkIndex
            # per chosen node carries assignments across this group's
            # placements so they don't double-book
            ask_res = tg.combined_resources()
            wants_ports = bool(ask_res.reserved_port_asks()
                               or ask_res.dynamic_port_count())
            net_idx: Dict[int, object] = {}

            n_feasible = int(tgt.feasible[: len(nodes)].sum())
            for i, req in enumerate(reqs):
                metrics = ctx.new_metrics()
                metrics.nodes_in_pool = len(nodes)
                metrics.nodes_evaluated = len(nodes)
                if founds[i]:
                    ni = int(choices[i])
                    node = cluster.nodes[ni]
                    option = RankedNode(node=node)
                    option.final_score = float(scores[i])
                    option.score_meta["normalized-score"] = option.final_score
                    metrics.scores[f"{node.id}.normalized-score"] = option.final_score
                    if wants_ports:
                        from ..structs.network import NetworkIndex

                        idx = net_idx.get(ni)
                        if idx is None:
                            idx = net_idx[ni] = NetworkIndex(node)
                            idx.add_allocs(ctx.proposed_allocs(node.id))
                        ports, err = idx.assign_ports(ask_res)
                        if err:
                            metrics.exhaust_node("ports")
                            commit(req, None)
                            continue
                        option.allocated_ports = ports
                    commit(req, option)
                    continue
                if preemption_enabled:
                    option = self._preempt_fallback(ctx, job, tg, nodes, req,
                                                    attempt)
                    if option is not None:
                        commit(req, option)
                        continue
                    metrics = ctx.metrics or metrics
                # attribute the failure the way the host path would: nodes
                # masked by constraints/drivers are "filtered", nodes that
                # passed feasibility but didn't fit are "exhausted"
                # (reference feasible.go filter vs rank.go exhaust metrics)
                masked = len(nodes) - n_feasible
                if masked:
                    metrics.nodes_filtered += masked
                    metrics.constraint_filtered["task group constraints"] = (
                        metrics.constraint_filtered.get("task group constraints", 0)
                        + masked)
                if n_feasible > 0:
                    metrics.exhaust_node("resources")
                commit(req, None)

    def _preempt_fallback(self, ctx, job, tg, nodes, req,
                          attempt: int) -> Optional[RankedNode]:
        penalty = frozenset({req.ignore_node}) if req.ignore_node else frozenset()
        option = select_best_node(
            ctx, job, tg, nodes,
            algorithm=(enums.SCHED_ALG_BINPACK
                       if self.algorithm == enums.SCHED_ALG_TPU_BINPACK
                       else self.algorithm),
            preemption_enabled=True,
            penalty_nodes=penalty,
            attempt=attempt,
        )
        return option
