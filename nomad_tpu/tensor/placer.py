"""TPUPlacer: batched placement behind SchedulerAlgorithm="tpu-binpack"
(the new algorithm value plugging into the reference's enum,
nomad/structs/operator.go:199-255).

Lowering strategy per evaluation:
  1. one ClusterTensors build (nodes + proposed usage),
  2. per task group: host-precompiled feasibility/affinity/spread arrays,
     device/core count columns, and distinct_property cap tables,
  3. one jitted solve_task_group scan placing all of the group's
     requests with full cross-placement visibility,
  4. commits mapped back through the scheduler's commit callback so the
     plan object and ctx.proposed_allocs stay authoritative. Exact port
     numbers, device instance ids, and core ids are assigned host-side
     per chosen node after the solve (counts were fit on-device).

Preemption stays host-side: when the kernel finds no fit and preemption
is enabled, the per-request fallback runs the host NodeScorer preemption
path (reference rank.go:205-587's preemption fallback arm). A request
whose post-solve id assignment fails (NUMA "require" mispredicted by
count-fit, overlapping device asks) falls back to the host selector for
that request alone.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..structs import Job, Node, enums
from ..scheduler.context import EvalContext
from ..scheduler.rank import NodeScorer, RankedNode, select_best_node
from ..scheduler.reconcile import PlacementRequest
from .cluster import ClusterTensors, build_task_group_tensors, _pad_pow2


class TPUPlacer:
    """Placer implementation: dense-tensor batch solve on the device."""

    def __init__(self, algorithm: str = enums.SCHED_ALG_BINPACK):
        # fit formula to use on the device; "tpu-binpack" keeps BestFit
        self.algorithm = algorithm

    def place(
        self,
        ctx: EvalContext,
        job: Job,
        requests: Sequence[PlacementRequest],
        nodes: Sequence[Node],
        commit,
        *,
        batch: bool = False,
        preemption_enabled: bool = False,
        attempt: int = 0,
    ) -> None:
        from .kernels import pack_solve_args, solve_task_group_fused

        if not nodes:
            for req in requests:
                m = ctx.new_metrics()
                m.nodes_in_pool = 0
                commit(req, None)
            return

        # Per-eval tie-break permutation, same seed discipline as the
        # host path's node shuffle (reference scheduler/util.go:167
        # shuffleNodes): scores are order-invariant, but the kernel's
        # argmax tie-breaks by priority order — without it every
        # concurrently-racing worker picks the same winners among
        # equal-scoring nodes and the plan applier rejects all but one
        # (optimistic-concurrency livelock). The permutation rides INTO
        # the kernel so the host-side node order stays canonical and the
        # per-node arrays stay cacheable across evals (ClusterStatic).
        cluster = ClusterTensors.build(ctx, nodes)
        nodes = cluster.nodes
        # crc32, not hash(): the seed must be deterministic ACROSS
        # processes (leader failover replaying an eval must explore the
        # same permutation), and hash() is salted per process
        seed = zlib.crc32(f"{ctx.eval_id}:{attempt}".encode())
        tie_perm = np.random.default_rng(seed).permutation(
            cluster.n_pad).astype(np.int32)

        # group requests per task group, preserving intra-group order
        groups: Dict[str, List[PlacementRequest]] = {}
        order: List[str] = []
        for req in requests:
            name = req.task_group.name
            if name not in groups:
                groups[name] = []
                order.append(name)
            groups[name].append(req)

        for gi, name in enumerate(order):
            reqs = groups[name]
            tg = reqs[0].task_group
            if gi > 0:  # build() already computed usage for the first group
                cluster.refresh_usage(ctx)

            tgt = build_task_group_tensors(ctx, job, tg, cluster,
                                           algorithm=self.algorithm)

            k = len(reqs)
            k_pad = _pad_pow2(k, floor=1)
            penalty_idx = np.full(k_pad, -1, dtype=np.int32)
            active = np.zeros(k_pad, dtype=bool)
            active[:k] = True
            for i, req in enumerate(reqs):
                if req.ignore_node:
                    penalty_idx[i] = cluster.node_index.get(req.ignore_node, -1)

            # device/core count columns extend the dense dims per group
            has_extra = tgt.extra_ask is not None and len(tgt.extra_ask)
            if has_extra:
                avail = np.concatenate([cluster.available, tgt.extra_cap], axis=1)
                used = np.concatenate([cluster.used, tgt.extra_used], axis=1)
                ask = np.concatenate([tgt.ask, tgt.extra_ask])
            else:
                avail, used, ask = cluster.available, cluster.used, tgt.ask

            packed = pack_solve_args(
                avail, used, tgt.placed_tg, tgt.placed_job,
                ask, tgt.feasible, tgt.affinity_boost, penalty_idx, active,
                tgt.spread_val_id, tgt.spread_val_ok, tgt.spread_counts,
                tgt.spread_desired, tgt.spread_has_targets, tgt.spread_weight,
                -1.0, tgt.tg_count, tgt.dh_job, tgt.dh_tg, tgt.spread_alg,
                dev_affinity=tgt.dev_affinity,
                dp_val_id=tgt.dp_val_id, dp_val_ok=tgt.dp_val_ok,
                dp_counts0=tgt.dp_counts, dp_limit=tgt.dp_limit,
                tie_perm=tie_perm)
            out = np.asarray(solve_task_group_fused(*packed))  # one readback
            choices = out[0].astype(np.int64)
            founds = out[1] > 0.5
            scores = out[2]

            # exact port numbers / device instances / core ids are
            # host-side, per chosen node, after the solve (the kernel only
            # fit-checked the counts); per-node indexes carry assignments
            # across this group's placements so they don't double-book
            ask_res = ctx.tg_resources(tg)
            wants_ports = bool(ask_res.reserved_port_asks()
                               or ask_res.dynamic_port_count())
            wants_devices = bool(ask_res.devices)
            wants_cores = bool(ask_res.cores)
            numa_pol = "none"
            if wants_cores:
                from ..scheduler.devices import combined_numa_affinity

                numa_pol = combined_numa_affinity(tg)
            net_idx: Dict[int, object] = {}
            dev_idx: Dict[int, object] = {}
            core_used: Dict[int, set] = {}

            n_feasible = int(tgt.feasible[: len(nodes)].sum())
            for i, req in enumerate(reqs):
                metrics = ctx.new_metrics()
                metrics.nodes_in_pool = len(nodes)
                metrics.nodes_evaluated = len(nodes)
                if founds[i]:
                    ni = int(choices[i])
                    node = cluster.nodes[ni]
                    option = RankedNode(node=node)
                    option.final_score = float(scores[i])
                    option.score_meta["normalized-score"] = option.final_score
                    metrics.scores[f"{node.id}.normalized-score"] = option.final_score
                    if wants_ports:
                        from ..structs.network import NetworkIndex

                        idx = net_idx.get(ni)
                        if idx is None:
                            idx = net_idx[ni] = NetworkIndex(node)
                            idx.add_allocs(ctx.proposed_allocs(node.id))
                        ports, err = idx.assign_ports(ask_res)
                        if err:
                            metrics.exhaust_node("ports")
                            commit(req, None)
                            continue
                        option.allocated_ports = ports
                    if wants_devices or wants_cores:
                        ok = self._assign_ids(ctx, ask_res, numa_pol, ni, node,
                                              option, dev_idx, core_used)
                        if not ok:
                            # count-fit admitted a node the exact id
                            # assignment can't satisfy (NUMA require /
                            # overlapping asks): host selector for this
                            # request alone
                            option = self._host_one(ctx, job, tg, nodes, req,
                                                    batch, preemption_enabled,
                                                    attempt)
                            commit(req, option)
                            if option is not None:
                                # the fallback assigned ids on its own
                                # node; drop that node's caches so later
                                # kernel placements rebuild them from the
                                # committed plan instead of double-booking
                                self._invalidate_node(
                                    cluster, option.node.id,
                                    net_idx, dev_idx, core_used)
                            continue
                    commit(req, option)
                    continue
                if preemption_enabled:
                    option = self._preempt_fallback(ctx, job, tg, nodes, req,
                                                    batch, attempt)
                    if option is not None:
                        commit(req, option)
                        # evictions + the fallback's own id assignments
                        # invalidate this node's port/device/core caches
                        self._invalidate_node(cluster, option.node.id,
                                              net_idx, dev_idx, core_used)
                        continue
                    metrics = ctx.metrics or metrics
                # attribute the failure the way the host path would: nodes
                # masked by constraints/drivers are "filtered", nodes that
                # passed feasibility but didn't fit are "exhausted"
                # (reference feasible.go filter vs rank.go exhaust metrics)
                masked = len(nodes) - n_feasible
                if masked:
                    metrics.nodes_filtered += masked
                    metrics.constraint_filtered["task group constraints"] = (
                        metrics.constraint_filtered.get("task group constraints", 0)
                        + masked)
                if n_feasible > 0:
                    metrics.exhaust_node("resources")
                commit(req, None)

    def _assign_ids(self, ctx, ask_res, numa_pol: str, ni: int, node,
                    option: RankedNode, dev_idx: Dict[int, object],
                    core_used: Dict[int, set]) -> bool:
        """Post-solve concrete id assignment for one placement on the
        chosen node. Per-node indexes live for the group's whole pass so
        sibling placements never double-book. A False return leaves any
        staged device instances reserved — conservative, and only
        reachable on count-fit mispredictions."""
        from ..scheduler.devices import DeviceIndex, select_cores, used_cores

        proposed = None
        if ask_res.devices:
            idx = dev_idx.get(ni)
            if idx is None:
                proposed = ctx.proposed_allocs(node.id)
                idx = dev_idx[ni] = DeviceIndex(node, proposed)
            assignment = idx.assign(ask_res.devices, ctx.regex_cache,
                                    ctx.version_cache)
            if assignment is None:
                return False
            option.allocated_devices = assignment
        if ask_res.cores:
            taken = core_used.get(ni)
            if taken is None:
                if proposed is None:
                    proposed = ctx.proposed_allocs(node.id)
                taken = core_used[ni] = used_cores(proposed)
            cores = select_cores(node, (), int(ask_res.cores), numa_pol,
                                 taken=taken)
            if cores is None:
                return False
            taken.update(cores)
            option.allocated_cores = cores
        return True

    @staticmethod
    def _invalidate_node(cluster, node_id: str, *caches: Dict[int, object]) -> None:
        ni = cluster.node_index.get(node_id)
        if ni is not None:
            for cache in caches:
                cache.pop(ni, None)

    def _host_algorithm(self) -> str:
        return (enums.SCHED_ALG_BINPACK
                if self.algorithm == enums.SCHED_ALG_TPU_BINPACK
                else self.algorithm)

    def _host_one(self, ctx, job, tg, nodes, req, batch: bool,
                  preemption_enabled: bool, attempt: int) -> Optional[RankedNode]:
        penalty = frozenset({req.ignore_node}) if req.ignore_node else frozenset()
        return select_best_node(
            ctx, job, tg, nodes,
            batch=batch,
            algorithm=self._host_algorithm(),
            preemption_enabled=preemption_enabled,
            penalty_nodes=penalty,
            attempt=attempt,
        )

    def _preempt_fallback(self, ctx, job, tg, nodes, req, batch: bool,
                          attempt: int) -> Optional[RankedNode]:
        return self._host_one(ctx, job, tg, nodes, req, batch,
                              preemption_enabled=True, attempt=attempt)
