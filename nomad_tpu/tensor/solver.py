"""Batched bulk-solve service: one device launch for many evals.

The device tunnel charges ~100ms of fixed latency per synchronous
readback at ~3.5MB/s (measured in-round); at C2M scale (500 evals x
4,000 allocs) per-eval round trips alone would be ~1 minute of wall
clock. Racing scheduler workers therefore don't talk to the device
directly on the bulk path: they enqueue solve requests here and block
on a future, while ONE service thread batches compatible requests into
a single kernels.solve_bulk_multi launch whose usage carry never
leaves the device between launches. Per eval, the wire moves one ask
row + scalars in and one (N,) int16 counts row out; the fixed latency
amortizes across the batch. Batching is demand-driven: while a launch
is in flight, newly arriving requests queue up and form the next
batch (backpressure, not timers, sets the batch size).

This is the "solver service" split SURVEY.md §2.5 calls for: cheap
local control-plane work on the host, batched dense solves on the
accelerator, one serialized commit point (the plan applier) unchanged.

Correctness contract: the device usage carry is an optimistic overlay
(base = store usage at the last resync, plus every solve since), and
the serialized plan applier remains the gate — it re-verifies every
placement against real state (core/plan_apply.py) exactly as for
host-solved plans, so drift can only cost throughput, never
correctness. Drift is then actively repaired instead of tolerated:

- every solve opens an in-flight LEDGER entry (per-node counts + ask);
- the scheduler invokes a plan post-apply hook (structs/plan.py
  post_apply_hooks) -> confirm(): a fully-committed solve just closes
  its entry (its usage is now in the store), while rejected nodes
  queue NEGATIVE usage corrections that the next launch scatter-adds
  into the carry — phantom usage from rejected placements never
  outlives one launch;
- resync (every RESYNC_SOLVES solves, on node-set change, or when the
  correction queue overflows) rebuilds the carry as committed store
  usage PLUS the still-open ledger entries, so in-flight work is never
  dropped from the overlay.

Without the ledger the carry both leaks rejected-placement phantoms
(solve shortfalls -> blocked-eval retry storms as the cluster fills)
and forgets in-flight solves at resync (double-booking -> rejection
bursts); measured in-round, that fed a tail where the last 10% of a
2M-alloc run took longer than the first 90%.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from ..core.metrics import REGISTRY
from ..obs import RECORDER, TRACER

_STOP = object()


def warm_launch(fn, shape_key, warm: set):
    """Shape-keyed launch window around one kernel launch: a warm shape
    runs under a hard jit_guard.no_retrace window (zero new compiles,
    implicit transfers raise), a cold shape may compile once and then
    marks itself warm. Either way the launch lands in the nomadjit
    ledger (no-op unless NOMAD_TPU_SAN=1) with its warm/cold standing.

    Callers jax.device_put EVERY argument first — committed jax.Arrays
    and bare numpy hit different jit cache entries, so a mixed diet
    would read as a retrace — and read back through a single
    jax.device_get, the launch's only host sync. Shared by the placer's
    per-eval launch sites and the incremental state's delta scatters."""
    import contextlib

    from ..analysis import launch_ledger
    from .jit_guard import count_compiles, no_retrace

    is_warm = shape_key in warm

    @contextlib.contextmanager
    def _window():
        name = getattr(fn, "__name__", str(fn))
        with launch_ledger.window(name, key=shape_key, warm=is_warm):
            if is_warm:
                with no_retrace(fn):
                    yield
            else:
                with count_compiles(fn):
                    yield
                warm.add(shape_key)

    return _window()


class BatchContext:
    """Rendezvous for one `Worker.process_batch` under "tpu-solve": the
    worker opens a context sized to the dequeued batch, each member eval
    runs inside it (`batch_member`), and the service thread holds the
    next launch open while members that may still submit their FIRST
    bulk solve are running — so a whole `dequeue_batch` result lands in
    ONE joint `tensor/batch_solver.solve_batch` launch instead of
    fragmenting across arrival timing. A member counts as "settled" the
    moment it submits a solve (it is in the queue) or when its run
    returns without one (host path, no-op eval, failure) — either way
    the service never waits on a member that cannot contribute, and the
    wait itself is deadline-bounded (JOINT_WAIT_S) so a wedged member
    degrades the batch to two launches instead of stalling it."""

    __slots__ = ("_lock", "_pending", "expected")

    def __init__(self, expected: int):
        self._lock = threading.Lock()
        self.expected = expected
        self._pending = expected

    def settle(self) -> None:
        with self._lock:
            self._pending -= 1

    def pending(self) -> int:
        with self._lock:
            return self._pending


_batch_tls = threading.local()


def current_batch() -> Optional[BatchContext]:
    return getattr(_batch_tls, "ctx", None)


def open_batch(expected: int) -> BatchContext:
    return BatchContext(expected)


class batch_member:
    """Context manager run by each member eval's thread: binds the
    BatchContext to the thread so the placer's solve call (deep in the
    scheduler stack) finds it, and settles the member on exit if it
    never submitted a joint solve."""

    def __init__(self, ctx: Optional[BatchContext]):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            _batch_tls.ctx = self._ctx
            _batch_tls.settled = False
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            if not getattr(_batch_tls, "settled", True):
                self._ctx.settle()
            _batch_tls.ctx = None
            _batch_tls.settled = True
        return False


def _settle_current_member() -> Optional[BatchContext]:
    """Mark the calling thread's batch member as settled (first joint
    solve submitted); returns the context, or None outside a batch."""
    ctx = current_batch()
    if ctx is not None and not getattr(_batch_tls, "settled", True):
        _batch_tls.settled = True
        ctx.settle()
    return ctx


def ensure_resident(static, feas_base, aff, mesh=None):
    """Device-resident (capacity, mask, affinity) arrays for one
    ClusterStatic, uploaded once and cached in static.device_arrays —
    masks/boosts keyed by host-array identity (the static's mask_cache /
    aff_cache hold the strong refs, so ids can't be recycled). The ONE
    place the cache-key protocol lives; used by the service (single and
    mesh layouts, distinguished by a cache-key tag) and the placer's
    single-eval fused path."""
    import jax

    if mesh is None:
        put_mat = put_row = jax.device_put
        tag = ""
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mat_sh = NamedSharding(mesh, P("nodes", None))
        row_sh = NamedSharding(mesh, P("nodes"))
        put_mat = lambda x: jax.device_put(x, mat_sh)  # noqa: E731
        put_row = lambda x: jax.device_put(x, row_sh)  # noqa: E731
        tag = "sh"
    da = static.device_arrays
    avail = da.get("avail" + tag)
    if avail is None:
        avail = da["avail" + tag] = put_mat(
            static.available.astype(np.float32))
    mkey = ("m" + tag, id(feas_base))
    m = da.get(mkey)
    if m is None:
        m = da[mkey] = put_row(feas_base)
    akey = ("a" + tag, id(aff))
    a = da.get(akey)
    if a is None:
        a = da[akey] = put_row(aff.astype(np.float32))
    return avail, m, a


class _Request:
    __slots__ = ("static", "feas_base", "aff", "ask", "k", "tg_count",
                 "seed", "used_fn", "used_dev_fn", "future", "token",
                 "joint", "batch_ctx")

    def __init__(self, static, feas_base, aff, ask, k, tg_count, seed,
                 used_fn, joint=False, batch_ctx=None, used_dev_fn=None):
        self.static = static
        self.feas_base = feas_base
        self.aff = aff
        self.ask = ask
        self.k = k
        self.tg_count = tg_count
        self.seed = seed
        # called at RESYNC time for a fresh committed-usage base; a base
        # captured at enqueue time goes stale under queue depth and
        # loses usage whose ledger entries already closed (measured
        # in-round: the 2M run's 1% rejection cascade)
        self.used_fn = used_fn
        # optional device-resident base: (mesh) -> committed-usage twin
        # on device (tensor/incremental.py), letting the resync fold
        # ledger entries with one scatter instead of shipping an O(N)
        # host rebuild. None or a failed call falls back to used_fn.
        self.used_dev_fn = used_dev_fn
        self.future = Future()
        self.token = 0
        self.joint = joint          # solve via the batch auction tier
        self.batch_ctx = batch_ctx  # worker-batch rendezvous, or None


class _LedgerEntry:
    """One in-flight solve: where its placements went, awaiting the
    plan outcome."""

    __slots__ = ("static", "idx", "counts", "ask", "born")

    def __init__(self, static, idx, counts, ask, born):
        self.static = static
        self.idx = idx        # (M,) node rows with placements
        self.counts = counts  # (M,) placement counts per row
        self.ask = ask        # (D,) per-placement usage
        self.born = born


class _Inflight:
    """One dispatched-but-unfetched launch: device handles for the
    outputs plus everything the deferred fetch needs to register the
    ledger entries, account stats, and resolve the workers' futures.
    JAX dispatch is async — holding these handles costs nothing until
    jax.device_get, which is the launch's ONLY host sync."""

    __slots__ = ("rs", "static", "counts", "info", "gathers", "rounds",
                 "joint", "sharded", "mesh_devices", "g", "resync",
                 "t0", "t_dispatched")

    def __init__(self, rs, static, counts, info, gathers, rounds, joint,
                 sharded, mesh_devices, g, resync, t0, t_dispatched):
        self.rs = rs
        self.static = static
        self.counts = counts        # (G, N) device handle
        self.info = info            # (6,) device handle (joint) or None
        self.gathers = gathers      # scalar device handle (joint+mesh)
        self.rounds = rounds        # (G,) device handle (greedy+mesh)
        self.joint = joint
        self.sharded = sharded
        self.mesh_devices = mesh_devices
        self.g = g
        self.resync = resync
        self.t0 = t0                # perf_counter at dispatch start
        self.t_dispatched = t_dispatched  # perf_counter at dispatch end


class BulkSolverService:
    G_PAD = 16          # evals per launch (padded; k=0 rows are no-ops)
    MAX_K = 32767       # int16 counts ceiling per eval
    RESYNC_SOLVES = 64  # overlay refresh cadence (external usage churn)
    CORRECTIONS = 64    # sparse correction slots per launch
    LEDGER_TTL = 60.0   # s before an unconfirmed solve is presumed dead
    JOINT_WAIT_S = 0.25  # max hold for worker-batch rendezvous members

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # single-entry device state: (static, used_dev, solves_since_sync).
        # One entry only — a new node-set version replaces it, and the
        # strong static ref keeps id()-keyed device_arrays coherent.
        self._state = None
        self._token = 0
        self._ledger: Dict[int, _LedgerEntry] = {}
        self._corrections: List[tuple] = []  # (node_row, delta_vec)
        # mesh scale-out: when the process owns >1 accelerator, the
        # usage carry + capacity/mask rows shard over a node-axis mesh
        # and launches go through solve_bulk_multi_sharded (ONE
        # all-gather per eval — tensor/sharding.py). Resolved lazily on
        # the service thread; _mesh stays None on single-device hosts.
        self._mesh = None
        self._mesh_resolved = False
        self._mesh_solve = None
        self._mesh_solve_joint = None
        # launch telemetry. compiles/retraces split by warmup state:
        # the first launch of a (tier, g_pad, n_pad, d) shape may
        # compile (stats["compiles"]); any cache growth after that is a
        # retrace and raises jit_guard.RetraceError (stats["retraces"]
        # counts them for the agent stats surface before propagating)
        self.stats = {"launches": 0, "solves": 0, "resyncs": 0,
                      "launch_s": 0.0, "corrections": 0, "sharded": 0,
                      "joint_launches": 0, "joint_solves": 0,
                      "auction_won": 0, "auction_rounds": 0,
                      "joint_score": 0.0, "greedy_score": 0.0,
                      "compiles": 0, "retraces": 0,
                      # pipeline telemetry: launches whose fetch was
                      # deferred behind a newer dispatch, host time spent
                      # off the fetch while a launch ran, device-window
                      # time, and the sharded launches' collective count
                      "pipelined": 0, "overlap_s": 0.0, "busy_s": 0.0,
                      "allgathers": 0, "mesh_devices": 0}
        self._warm_shapes: set = set()
        # double buffer: the one dispatched-but-unfetched launch. Only
        # the service thread touches it. While it rides the device, the
        # host resolves the PREVIOUS batch's futures (workers verify +
        # commit their AllocBlocks) and collects/dispatches the next —
        # the solve/apply overlap the c2m rung measures.
        self._inflight: Optional["_Inflight"] = None

    def _resolve_mesh(self, n_pad: int):
        """Largest power-of-two device mesh that divides the padded node
        axis, or None for single-device. NOMAD_TPU_MESH_DEVICES caps the
        mesh (1 forces single-device) so bench sweeps and parity tests
        can pin a size without re-execing under a different XLA device
        count; resolved once per service instance."""
        if not self._mesh_resolved:
            self._mesh_resolved = True
            import os

            import jax

            devs = jax.devices()
            cap = int(os.environ.get("NOMAD_TPU_MESH_DEVICES", "0") or 0)
            if cap > 0:
                devs = devs[:cap]
            if len(devs) > 1:
                from .sharding import (make_solve_batch_sharded,
                                       make_solve_bulk_multi_sharded,
                                       node_mesh)

                n = 1 << (len(devs).bit_length() - 1)
                self._mesh = node_mesh(devs[:n])
                self._mesh_solve = make_solve_bulk_multi_sharded(self._mesh)
                self._mesh_solve_joint = make_solve_batch_sharded(self._mesh)
                with self._lock:
                    self.stats["mesh_devices"] = n
                REGISTRY.set_gauge("nomad.solver.mesh_devices", n)
        if self._mesh is None:
            return None
        n_dev = len(self._mesh.devices.reshape(-1))
        return self._mesh if n_pad % n_dev == 0 else None

    # -- caller side (scheduler worker threads) --

    def solve(self, *, static, feas_base, aff, ask, k, tg_count, seed,
              used_fn, joint=False, used_dev_fn=None):
        """Blocking solve of one fresh-placement bulk eval ->
        ((N_pad,) int64 per-node counts in canonical order, token).
        The caller must arrange for confirm(token, rejected_node_ids)
        to run once the plan containing these placements is applied
        (plan.post_apply_hooks). With joint=True ("tpu-solve") the
        request is solved by the global-batch auction kernel together
        with every compatible request in the same launch; a worker-batch
        BatchContext bound to the calling thread rides along so the
        launch waits for the rest of the dequeued batch."""
        req = _Request(static, feas_base, aff,
                       np.asarray(ask, dtype=np.float32), int(k),
                       float(tg_count), np.uint32(seed), used_fn,
                       joint=joint,
                       batch_ctx=current_batch() if joint else None,
                       used_dev_fn=used_dev_fn)
        # put BEFORE ensure: the service thread clears self._thread
        # before its final stop-drain, so a request racing stop() is
        # either caught by that drain (failed, answered) or observes
        # the cleared slot here and starts a fresh thread — ensure
        # first could watch a thread that exits without ever reading
        # the queue, stranding the caller on the future (found by the
        # solve_batch modelcheck scenario)
        self._q.put(req)
        self._ensure_thread()
        if req.batch_ctx is not None:
            # settle AFTER the put: the service may launch without a
            # member whose settle it observed but whose request it
            # didn't — never the reverse
            _settle_current_member()
        # runs on the worker thread inside the eval's trace bind, so
        # the wait (queue + rendezvous + device launch) lands on the
        # eval's own span chain
        with TRACER.span("solver.wait", k=int(k), joint=bool(joint)):
            result = req.future.result()
        return result, req.token

    def confirm(self, token: int, rejected_node_ids) -> None:
        """Plan outcome for one solve: close its ledger entry; queue
        negative usage corrections for placements the applier rejected
        (the whole node's placement list drops on a node rejection)."""
        with self._lock:
            entry = self._ledger.pop(token, None)
            if entry is None:
                return
            if not rejected_node_ids:
                return
            node_index = entry.static.node_index
            rows = {node_index.get(nid) for nid in rejected_node_ids}
            for i, row in enumerate(entry.idx):
                if row in rows:
                    self._corrections.append(
                        (row, -float(entry.counts[i]) * entry.ask))
                    self.stats["corrections"] += 1

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="bulk-solver", daemon=True)
                self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            self._q.put(_STOP)
            t.join(timeout=10.0)

    # -- service thread --

    def _retire(self) -> None:
        """Clear the thread slot BEFORE the final stop-drain: any
        solve() that puts after the drain finishes then sees the empty
        slot and starts a fresh thread instead of stranding (solve()
        puts before it checks, so a request the drain missed always
        has its ensure still ahead of it)."""
        with self._lock:
            self._thread = None

    def _run(self) -> None:
        import time as _time

        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                # queue drained: every worker that could feed the next
                # batch may be blocked on the in-flight launch's futures
                # — fetch it (resolving them) BEFORE parking on the
                # queue, or the pipeline deadlocks on an empty queue
                self._fetch_inflight()
                req = self._q.get()
            if req is _STOP:
                self._fetch_inflight()
                self._retire()
                self._drain_failed()
                return
            batch = [req]
            # drain whatever queued while the previous launch ran
            deadline = None
            while len(batch) < self.G_PAD:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    # worker-batch rendezvous: members of an open
                    # BatchContext that haven't settled yet may still
                    # submit — hold the launch (bounded) so the whole
                    # dequeued batch solves jointly
                    if not any(r.batch_ctx is not None
                               and r.batch_ctx.pending() > 0
                               for r in batch):
                        break
                    # spend the hold productively: drain the in-flight
                    # launch now so ITS workers verify/commit while the
                    # rendezvous waits
                    self._fetch_inflight()
                    if deadline is None:
                        deadline = _time.monotonic() + self.JOINT_WAIT_S
                    remain = deadline - _time.monotonic()
                    if remain <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=min(remain, 0.01))
                    except queue.Empty:
                        continue
                if nxt is _STOP:
                    self._retire()
                    self._flush(batch)
                    self._fetch_inflight()
                    self._drain_failed()
                    return
                batch.append(nxt)
            self._flush(batch)

    def _drain_failed(self) -> None:
        """Fail any request that raced the stop sentinel into the queue —
        its worker is blocked on the future and must not hang."""
        while True:
            try:
                nxt = self._q.get_nowait()
            except queue.Empty:
                return
            if nxt is not _STOP and not nxt.future.done():
                nxt.future.set_exception(
                    RuntimeError("bulk solver service stopped"))

    def _flush(self, batch: List[_Request]) -> None:
        # one launch per distinct (static, tier): mixed statics happen
        # only across a node-set version change, mixed tiers only while
        # an A/B run flips the algorithm — either way the greedy tier's
        # requests must never route through the auction arm, the
        # baseline has to stay pure
        groups = {}
        for r in batch:
            groups.setdefault((id(r.static), r.joint), []).append(r)
        for rs in groups.values():
            try:
                inflight = self._dispatch_group(rs)
            except Exception as e:  # propagate to every blocked worker
                # the launch may have consumed (donated) the usage carry
                # before failing — drop the state so the next solve
                # resyncs instead of feeding a deleted buffer back in
                self._state = None
                # the PREVIOUS launch's outputs are independent buffers;
                # drain it so its workers aren't stranded by our failure
                self._fetch_inflight()
                for r in rs:
                    if not r.future.done():
                        r.future.set_exception(e)
                continue
            # double buffer: fetch launch i only now that launch i+1 is
            # queued behind it on the device — i's workers plan-verify
            # and commit while the device solves i+1
            self._fetch_inflight(pipelined=True)
            self._inflight = inflight

    def _fetch_inflight(self, pipelined: bool = False) -> None:
        """Drain the one unfetched launch, if any: register its ledger
        entries, account stats, resolve its workers' futures. Must run
        before anything that rebuilds the carry from the ledger (resync,
        static change, stop) — an unfetched launch has no entries yet,
        so a base built without draining it would silently drop its
        usage from the overlay."""
        inf = self._inflight
        if inf is None:
            return
        self._inflight = None
        try:
            self._fetch(inf, pipelined=pipelined)
        except Exception as e:
            # readback failed: the carry chained off this launch is
            # suspect too — poison it so the next dispatch resyncs
            self._state = None
            for r in inf.rs:
                if not r.future.done():
                    r.future.set_exception(e)

    def _launch_guard(self, fn, shape_key):
        """no_retrace window + warmup accounting for one launch shape:
        the first launch of a shape may compile (stats["compiles"]);
        once a shape is warm any cache growth raises RetraceError and
        any implicit host transfer raises TransferGuard — both are perf
        bugs the tests pin at zero."""
        import contextlib

        from ..analysis import launch_ledger
        from .jit_guard import RetraceError, no_retrace

        @contextlib.contextmanager
        def window():
            warm = shape_key in self._warm_shapes
            win = no_retrace(fn, expect=0 if warm else 2)
            ledger = launch_ledger.window(
                getattr(fn, "__name__", str(fn)), key=shape_key, warm=warm)
            try:
                with ledger, win as counters:
                    yield
            except RetraceError:
                with self._lock:
                    self.stats["retraces"] += 1
                raise
            self._warm_shapes.add(shape_key)
            if counters["compiles"]:
                with self._lock:
                    self.stats["compiles"] += counters["compiles"]
        return window()

    def _resync_base(self, r, static, mesh, d, ledger_entries):
        """Fresh usage carry for a resync: committed usage + open ledger
        entries. Preferred source is the incremental feed's
        device-resident twin (tensor/incremental.py) — the ledger folds
        on-device in one scatter and the O(N) host gather + device_put
        never happens; any miss or failure falls back to the exact host
        path (used_fn + host fold + ship)."""
        import jax

        if r.used_dev_fn is not None:
            try:
                dev_base = r.used_dev_fn(mesh)
            except Exception:
                dev_base = None
            if dev_base is not None:
                try:
                    return self._fold_base_scatter(dev_base, static, mesh,
                                                   d, ledger_entries)
                except Exception:
                    pass        # repairable: host path below is exact
        base = np.asarray(r.used_fn(), dtype=np.float32).copy()
        for idx, counts, ask in ledger_entries:
            base[idx] += counts[:, None].astype(np.float32) * ask[None, :]
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(base, NamedSharding(mesh, P("nodes", None)))
        else:
            return jax.device_put(base)

    def _fold_base_scatter(self, dev_base, static, mesh, d,
                           ledger_entries):
        """Fold open-ledger + per-eval in-flight (overlay) usage into
        the feed's device base with ONE non-donating scatter launch.
        Non-donating on purpose: the solve kernels donate their usage
        carry (argument 0), and the feed's twin must survive this solve
        — the fold's fresh output array is what enters the donation
        chain. Zero deltas still scatter: the copy IS the protection."""
        import jax

        from .incremental import _scatter_fn
        from .overlay import INFLIGHT

        n_pad = static.n_pad
        rows_list, delta_list = [], []
        for idx, counts, ask in ledger_entries:
            rows_list.append(np.asarray(idx, dtype=np.int32))
            delta_list.append(counts[:, None].astype(np.float32)
                              * np.asarray(ask, np.float32)[None, :])
        tmp = np.zeros((n_pad, d), dtype=np.float32)
        INFLIGHT.fold(tmp[: len(static.nodes)], static.node_index)
        nz = np.nonzero(np.any(tmp != 0.0, axis=1))[0]
        if nz.size:
            rows_list.append(nz.astype(np.int32))
            delta_list.append(tmp[nz])
        total = sum(len(x) for x in rows_list)
        bucket = 8
        while bucket < total:
            bucket *= 2
        idx = np.zeros(bucket, dtype=np.int32)
        delta = np.zeros((bucket, d), dtype=np.float32)
        pos = 0
        for rr, dd in zip(rows_list, delta_list):
            idx[pos: pos + len(rr)] = rr
            delta[pos: pos + len(rr)] = dd
            pos += len(rr)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .sharding import make_state_scatter_sharded

            n_dev = len(mesh.devices.reshape(-1))
            fn = make_state_scatter_sharded(mesh, donate=False)
            rep = NamedSharding(mesh, P())
            idx = jax.device_put(idx, rep)
            delta = jax.device_put(delta, rep)
            key = ("statefold-sh", n_pad, d, bucket, n_dev)
        else:
            fn = _scatter_fn(donate=False)
            idx, delta = jax.device_put((idx, delta))
            key = ("statefold", n_pad, d, bucket)
        with self._launch_guard(fn, key):
            return fn(dev_base, idx, delta)

    def _device_arrays(self, static, rs, mesh=None):
        """Resident capacity + stacked per-eval mask/affinity arrays
        (node-axis sharded over `mesh` when given); the stacked (G, N)
        combinations are cached by the tuple of the underlying
        host-array ids — repeated batches of the same task-group shapes
        ship nothing."""
        import jax.numpy as jnp

        da = static.device_arrays
        rows_m, rows_a = [], []
        for r in rs:
            avail, m, a = ensure_resident(static, r.feas_base, r.aff,
                                          mesh=mesh)
            rows_m.append((id(r.feas_base), m))
            rows_a.append((id(r.aff), a))
        # joint solves always take the full padded width: padded rows
        # (k=0) exit the kernel loops immediately, and a single-row
        # joint warmup then compiles the SAME shape the production
        # batches run — a g=1 special case would bill a fresh g=G_PAD
        # XLA compile to the first real batch launch
        g_pad = (self.G_PAD if rs[0].joint
                 else 1 if len(rs) == 1 else self.G_PAD)
        while len(rows_m) < g_pad:
            rows_m.append(rows_m[0])
            rows_a.append(rows_a[0])
        # cache the stacked buffers only for UNIFORM batches (every row
        # the same mask/aff — the overwhelmingly common shape): mixed
        # compositions vary by arrival order, and caching each
        # permutation would pin unbounded device memory
        uniform = (all(i == rows_m[0][0] for i, _ in rows_m)
                   and all(i == rows_a[0][0] for i, _ in rows_a))
        skey = ("stack" + ("sh" if mesh is not None else ""), g_pad,
                rows_m[0][0], rows_a[0][0])
        stacked = da.get(skey) if uniform else None
        if stacked is None:
            # on-device stack: no host transfer
            stacked = (jnp.stack([m for _, m in rows_m]),
                       jnp.stack([a for _, a in rows_a]))
            if uniform:
                da[skey] = stacked
        return avail, stacked[0], stacked[1], g_pad

    def _dispatch_group(self, rs: List[_Request]) -> "_Inflight":
        """Build the launch inputs, ship them, and DISPATCH the solve —
        returning device handles without syncing. JAX dispatch is async:
        the returned _Inflight's outputs materialize while the host does
        other work, and the chained usage carry (donated argument 0)
        lets the NEXT dispatch queue behind this one device-side, so
        launch order alone guarantees every solve sees its predecessor's
        usage — never a stale carry — regardless of fetch timing."""
        from .kernels import solve_bulk_multi

        import jax
        import time as _time

        t0 = _time.perf_counter()
        static = rs[0].static
        d = static.available.shape[1]
        mesh = self._resolve_mesh(static.n_pad)
        state = self._state
        used_dev, since = None, 0
        if state is not None and state[0] is static:
            used_dev, since = state[1], state[2]

        with self._lock:
            need_resync = (used_dev is None
                           or since >= self.RESYNC_SOLVES
                           or len(self._corrections) > self.CORRECTIONS)
        if need_resync:
            # the resync base is committed usage + OPEN ledger entries.
            # A still-unfetched launch has no entries yet — drain it
            # first, or the rebuilt base silently drops its in-flight
            # usage (double-booking burst at the next commit wave)
            self._fetch_inflight()

        now = _time.time()
        with self._lock:
            # unconfirmed solves past the TTL belong to evals that died
            # between solve and submit; presume their placements never
            # committed and stop re-applying them at resync
            dead = [t for t, e in self._ledger.items()
                    if now - e.born > self.LEDGER_TTL]
            for t in dead:
                del self._ledger[t]
            if need_resync:
                # exact rebuild: committed usage + still-in-flight solves
                # (queued corrections target phantoms in the old carry —
                # the rebuild has none, so drop them)
                self._corrections.clear()
                ledger_entries = [(e.idx, e.counts, e.ask)
                                  for e in self._ledger.values()
                                  if e.static is static]
                corrections = []
            else:
                # take at most one launch's worth: confirm() may have
                # pushed past the cap while _fetch_inflight ran above
                # (the pre-check and this take are separate lock holds
                # now) — leftovers stay queued and trip the overflow
                # pre-check on the NEXT dispatch, which resyncs after
                # draining the inflight launch instead of silently
                # dropping corrections here
                corrections = self._corrections[:self.CORRECTIONS]
                self._corrections = self._corrections[self.CORRECTIONS:]
        if need_resync:
            used_dev = self._resync_base(rs[0], static, mesh, d,
                                         ledger_entries)
            since = 0
            with self._lock:
                self.stats["resyncs"] += 1

        cidx = np.zeros(self.CORRECTIONS, dtype=np.int32)
        cdelta = np.zeros((self.CORRECTIONS, d), dtype=np.float32)
        for i, (row, delta) in enumerate(corrections[:self.CORRECTIONS]):
            cidx[i] = row
            cdelta[i] = delta

        avail, feas, aff, g_pad = self._device_arrays(static, rs, mesh)
        g = len(rs)
        ask = np.zeros((g_pad, d), dtype=np.float32)
        k = np.zeros(g_pad, dtype=np.int32)
        tgc = np.ones(g_pad, dtype=np.float32)
        seeds = np.zeros(g_pad, dtype=np.uint32)
        for i, r in enumerate(rs):
            ask[i] = r.ask
            k[i] = r.k
            tgc[i] = r.tg_count
            seeds[i] = r.seed

        joint = rs[0].joint
        info = gathers = rounds = None
        n_dev = 0 if mesh is None else len(mesh.devices.reshape(-1))
        if mesh is None:
            # explicit shipment of the per-batch host rows so the
            # no_retrace transfer guard can outlaw every IMPLICIT
            # transfer inside the launch window
            ask, k, tgc, seeds, cidx, cdelta = jax.device_put(
                (ask, k, tgc, seeds, cidx, cdelta))
        else:
            # explicit REPLICATED shipment: a bare device_put here would
            # hand the sharded jit uncommitted single-device arrays —
            # the committed-vs-bare cache fork (one graph per layout) —
            # and letting the launch ship them implicitly is exactly
            # what the transfer guard below outlaws on the warm path
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(mesh, P())
            ask, k, seeds, cidx, cdelta = (
                jax.device_put(x, rep)
                for x in (ask, k, seeds, cidx, cdelta))
        if joint and mesh is None:
            from .batch_solver import solve_batch

            with self._launch_guard(solve_batch,
                                    ("joint", g_pad, static.n_pad, d)):
                new_used, counts, info = solve_batch(
                    used_dev, avail, feas, aff, ask, k, tgc, seeds,
                    cidx, cdelta, g=g_pad)
        elif joint:
            with self._launch_guard(
                    self._mesh_solve_joint,
                    ("joint-sh", g_pad, static.n_pad, d, n_dev)):
                new_used, counts, info, gathers = self._mesh_solve_joint(
                    used_dev, avail, feas, aff, ask, k, seeds, cidx,
                    cdelta, g=g_pad)
        elif mesh is not None:
            with self._launch_guard(
                    self._mesh_solve,
                    ("greedy-sh", g_pad, static.n_pad, d, n_dev)):
                new_used, counts, rounds = self._mesh_solve(
                    used_dev, avail, feas, aff, ask, k, seeds, cidx,
                    cdelta, g=g_pad)
        else:
            with self._launch_guard(solve_bulk_multi,
                                    ("greedy", g_pad, static.n_pad, d)):
                new_used, counts = solve_bulk_multi(
                    used_dev, avail, feas, aff, ask, k, tgc, seeds, cidx,
                    cdelta, g=g_pad)
        self._state = (static, new_used, since + g)
        t1 = _time.perf_counter()
        if mesh is not None:
            # dispatch-side span: the sharded launch is queued, the host
            # keeps running — the solve/apply overlap window opens here
            wall = _time.time()
            TRACER.add_span("solver.shard", wall - (t1 - t0), wall,
                            g=g, joint=bool(joint), mesh_devices=n_dev)
        RECORDER.record("solver", "launch", g=g, joint=bool(joint),
                        sharded=mesh is not None, resync=need_resync)
        return _Inflight(rs=rs, static=static, counts=counts, info=info,
                         gathers=gathers, rounds=rounds, joint=joint,
                         sharded=mesh is not None, mesh_devices=n_dev,
                         g=g, resync=need_resync, t0=t0, t_dispatched=t1)

    def _fetch(self, inf: "_Inflight", pipelined: bool = False) -> None:
        """The launch's ONLY host sync: read the counts (+ info/gather
        stats) back, register ledger entries, account stats, resolve the
        workers' futures. Everything between dispatch and this call is
        host time the device solve ran under — the overlap the
        nomad.solver.overlap_occupancy gauge reports."""
        import jax
        import time as _time

        g = inf.g
        t_f0 = _time.perf_counter()
        handles = [h for h in (inf.counts, inf.info, inf.gathers,
                               inf.rounds) if h is not None]
        got = list(jax.device_get(handles))
        counts_np = got.pop(0)
        info_np = got.pop(0) if inf.info is not None else None
        gathers_np = got.pop(0) if inf.gathers is not None else None
        rounds_np = got.pop(0) if inf.rounds is not None else None
        t_f1 = _time.perf_counter()
        born = _time.time()
        allg = 0
        if gathers_np is not None:
            allg = int(gathers_np)
        elif rounds_np is not None:
            allg = int(rounds_np[:g].sum())
        overlap = max(0.0, t_f0 - inf.t_dispatched)
        busy = max(0.0, t_f1 - inf.t_dispatched)
        # trace-less batch spans (the service thread serves many evals
        # at once); chain gap-attribution picks them up by time overlap,
        # like the raft spans
        TRACER.add_span("solver.launch", born - (t_f1 - inf.t0), born,
                        g=g, joint=bool(inf.joint), sharded=inf.sharded,
                        pipelined=pipelined)
        if inf.sharded:
            TRACER.add_span("solver.allgather", born - (t_f1 - t_f0),
                            born, gathers=allg,
                            per_eval=allg / max(g, 1))
        with self._lock:
            # counters share self._lock with the ledger: solve()/confirm()
            # mutate stats from API threads under the same lock
            self.stats["launches"] += 1
            self.stats["solves"] += g
            # host cost only: dispatch + fetch, NOT the device wait a
            # pipelined launch absorbed while the host worked elsewhere
            self.stats["launch_s"] += ((inf.t_dispatched - inf.t0)
                                       + (t_f1 - t_f0))
            self.stats["overlap_s"] += overlap
            self.stats["busy_s"] += busy
            self.stats["allgathers"] += allg
            if pipelined:
                self.stats["pipelined"] += 1
            if inf.sharded:
                self.stats["sharded"] += 1
            if info_np is not None:
                self.stats["joint_launches"] += 1
                self.stats["joint_solves"] += g
                self.stats["auction_won"] += int(info_np[5] > 0.5)
                self.stats["auction_rounds"] += int(info_np[4])
                self.stats["joint_score"] += float(
                    info_np[0] if info_np[5] > 0.5 else info_np[1])
                self.stats["greedy_score"] += float(info_np[1])
            for i, r in enumerate(inf.rs):
                row = counts_np[i]
                idx = np.nonzero(row)[0]
                self._token += 1
                r.token = self._token
                self._ledger[r.token] = _LedgerEntry(
                    inf.static, idx, row[idx].astype(np.int64), r.ask,
                    born)
            occupancy = (self.stats["overlap_s"] / self.stats["busy_s"]
                         if self.stats["busy_s"] > 0 else 0.0)
        # mirror the service stats into the Registry so /v1/metrics and
        # bench dumps carry them without reaching into the singleton
        # (REGISTRY is a leaf lock — taken after self._lock is dropped)
        REGISTRY.incr("nomad.solver.launches")
        REGISTRY.incr("nomad.solver.solves", g)
        if allg:
            REGISTRY.incr("nomad.solver.allgathers", allg)
        REGISTRY.set_gauge("nomad.solver.overlap_occupancy", occupancy)
        if info_np is not None:
            REGISTRY.incr("nomad.solver.auction_won",
                          int(info_np[5] > 0.5))
            REGISTRY.incr("nomad.solver.auction_rounds", int(info_np[4]))
            REGISTRY.incr("nomad.solver.joint_score", float(
                info_np[0] if info_np[5] > 0.5 else info_np[1]))
            REGISTRY.incr("nomad.solver.greedy_score", float(info_np[1]))
        for i, r in enumerate(inf.rs):
            r.future.set_result(counts_np[i].astype(np.int64))


_service: Optional[BulkSolverService] = None
_service_lock = threading.Lock()


def get_service() -> BulkSolverService:
    global _service
    if _service is None:
        with _service_lock:
            if _service is None:
                _service = BulkSolverService()
    return _service
