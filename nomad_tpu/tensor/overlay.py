"""In-flight usage overlay for the PER-EVAL solve paths.

The bulk C2M path serializes racing workers on the solver service's
device-resident carry, so concurrent solves see each other's placements
before they commit (tensor/solver.py). The per-eval kernel paths
(spread/constraints/distinct-hosts — one fused launch per eval) had no
such visibility: two workers racing at the same snapshot both fill the
same best-fit nodes to capacity, the applier rejects the loser's whole
node lists, and the spread rung's rejection rate ran ABOVE stock
(round 4 weak #5: 0.0018 vs 0.0; stock's log2-N candidate subsampling
decorrelates workers by accident).

This overlay is the host-side twin of the service's ledger: each
per-eval solve registers its placements' per-node usage deltas keyed by
node ID; every ClusterTensors usage gather folds the open entries in,
so the NEXT racing eval plans around them. Entries close through the
same plan post-apply hooks the service uses (confirmed usage is then in
the store; rejected nodes' deltas die with the entry), with a TTL
backstop for evals that die between solve and submit. Like the carry,
this is optimism-repair only — the serialized plan applier remains the
correctness gate.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

ENTRY_TTL = 60.0


class InflightOverlay:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[int, dict] = {}  # token -> entry
        self._token = 0
        self.stats = {"registered": 0, "confirmed": 0, "expired": 0}

    def register(self, deltas: Dict[str, object], plan) -> None:
        """Record one eval's in-flight per-node usage deltas
        ({node_id: vec}) and arrange for the plan outcome to close the
        entry (planner contract: hooks fire with the commit)."""
        if not deltas:
            return
        now = time.time()
        with self._lock:
            self._token += 1
            token = self._token
            self._entries[token] = {"deltas": deltas, "born": now,
                                    "plan": id(plan)}
            self.stats["registered"] += 1
        if plan is not None:
            plan.post_apply_hooks.append(
                lambda result, _t=token: self.confirm(
                    _t, getattr(result, "rejected_nodes", None) or ()))
        else:
            # no plan to hook (harness edge): rely on the TTL
            pass

    def confirm(self, token: int, rejected_node_ids) -> None:
        """Plan applied: committed usage is now in the store, rejected
        nodes never landed — either way the entry closes."""
        with self._lock:
            if self._entries.pop(token, None) is not None:
                self.stats["confirmed"] += 1

    def has_entries(self, exclude_plan=None) -> bool:
        """True when fold() would add anything: at least one live
        (non-TTL-expired) entry not owned by `exclude_plan`. Lets the
        incremental-state fast path hand out a shared read-only base
        instead of copying it just to fold nothing in."""
        now = time.time()
        exclude = id(exclude_plan) if exclude_plan is not None else None
        with self._lock:
            return any(
                now - e["born"] <= ENTRY_TTL
                and (e.get("plan") != exclude or exclude is None)
                for e in self._entries.values())

    def fold(self, used, node_index: Dict[str, int],
             exclude_plan=None) -> None:
        """Add every open entry's deltas into a canonical-order usage
        matrix (in place). Called from ClusterTensors usage gathers.
        `exclude_plan` skips the calling eval's OWN entries — its
        placements are already in the plan the usage recompute reads
        (double-counting them made multi-group evals see full nodes)."""
        now = time.time()
        exclude = id(exclude_plan) if exclude_plan is not None else None
        with self._lock:
            if not self._entries:
                return
            dead = [t for t, e in self._entries.items()
                    if now - e["born"] > ENTRY_TTL]
            for t in dead:
                del self._entries[t]
                self.stats["expired"] += 1
            entries = [e for e in self._entries.values()
                       if e.get("plan") != exclude or exclude is None]
        d = used.shape[1]
        for e in entries:
            for node_id, vec in e["deltas"].items():
                row = node_index.get(node_id)
                if row is not None:
                    used[row] += vec[:d]


INFLIGHT = InflightOverlay()
