"""Multi-chip sharding for the placement solve.

The long axis of this workload is nodes (SURVEY.md §5: the (jobs x nodes)
matrix is our "long context"). The solve is embarrassingly parallel over
nodes except for one global reduction per placement step (the argmax over
node scores) and one scatter (the usage update on the winner) — exactly
the shape of ring-reduce workloads, so it rides ICI:

    mesh = Mesh(devices, ("nodes",))
    available, used, feasible, ...  sharded P("nodes")   [row-sharded]
    spread tables, ask, flags       replicated P()
    per-step: local scores -> global argmax (XLA all-reduce over ICI)
              -> one-hot usage update (local on the owning shard)

With jit + NamedSharding constraints XLA inserts the collectives; there
is no hand-written NCCL/MPI analog to port (the reference's comm backend
is msgpack-RPC/Serf/Raft, SURVEY.md §2.5 — control-plane replication
stays host-side, this module only distributes the math).

Used by __graft_entry__.dryrun_multichip and the multi-chip benchmarks.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def node_mesh(devices: Sequence = None, axis: str = "nodes") -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def pad_node_axis(args: tuple, multiple: int) -> tuple:
    """Pad the node axis up to a multiple of the mesh size with infeasible
    dummy rows (available=0, feasible=False, spread_val_ok=False). The
    solve's argmax can never pick them, so choices stay valid indices into
    the real rows and scores are untouched — real clusters are rarely
    divisible by the device count."""
    n = args[0].shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return args
    args = list(args)

    def _pad(x, axis, value):
        x = np.asarray(x)
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return np.pad(x, widths, constant_values=value)

    args[0] = _pad(args[0], 0, 0)          # available
    args[1] = _pad(args[1], 0, 0)          # used0
    args[2] = _pad(args[2], 0, 0)          # placed_tg0
    args[3] = _pad(args[3], 0, 0)          # placed_job0
    args[5] = _pad(args[5], 0, False)      # feasible
    args[6] = _pad(args[6], 0, 0.0)        # affinity_boost
    args[7] = _pad(args[7], 0, 0.0)        # dev_affinity
    args[10] = _pad(args[10], 1, 0)        # spread_val_id
    args[11] = _pad(args[11], 1, False)    # spread_val_ok
    args[16] = _pad(args[16], 1, 0)        # dp_val_id
    args[17] = _pad(args[17], 1, False)    # dp_val_ok
    if len(args) > 25 and args[25] is not None:
        # tie_perm: dummy rows get the lowest priority, appended at the end
        args[25] = np.concatenate([
            np.asarray(args[25], np.int32), np.arange(n, n + pad, dtype=np.int32)])
    return tuple(args)


def shard_solve_args(mesh: Mesh, args: tuple, axis: str = "nodes"):
    """Device_put the solve_task_group argument tuple with node-axis rows
    sharded and everything else replicated. Pads the node axis to the
    mesh size first (see pad_node_axis).

    Argument order mirrors kernels.solve_task_group:
      0 available (N,D)   sharded   10 spread_val_id (S,N)  sharded ax1
      1 used0 (N,D)       sharded   11 spread_val_ok (S,N)  sharded ax1
      2 placed_tg0 (N,)   sharded   12 spread_counts0 (S,V) repl
      3 placed_job0 (N,)  sharded   13 spread_desired (S,V) repl
      4 ask (D,)          repl      14 spread_has_targets   repl
      5 feasible (N,)     sharded   15 spread_weight (S,)   repl
      6 affinity (N,)     sharded   16 dp_val_id (P,N)      sharded ax1
      7 dev_affinity (N,) sharded   17 dp_val_ok (P,N)      sharded ax1
      8 penalty_idx (K,)  repl      18 dp_counts0 (P,Vd)    repl
      9 active (K,)       repl      19 dp_limit (P,)        repl
                                    20..24 scalars          repl
                                    25 tie_perm (N,)        repl
    """
    args = pad_node_axis(args, int(np.prod(mesh.devices.shape)))
    specs = [
        P(axis, None), P(axis, None), P(axis), P(axis),
        P(), P(axis), P(axis), P(axis), P(), P(),
        P(None, axis), P(None, axis), P(), P(), P(), P(),
        P(None, axis), P(None, axis), P(), P(),
    ]
    specs += [P()] * (len(args) - len(specs))
    out = []
    for a, spec in zip(args, specs):
        out.append(a if a is None
                   else jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


def solve_task_group_sharded(mesh: Mesh, args: tuple, axis: str = "nodes"):
    """Run the placement solve with the node axis sharded over `mesh`.

    The same jitted kernel as the single-chip path: XLA propagates the
    input shardings through the scan and inserts ICI collectives for the
    global argmax each step.
    """
    from .kernels import solve_task_group

    sharded = shard_solve_args(mesh, args, axis)
    return solve_task_group(*sharded)
