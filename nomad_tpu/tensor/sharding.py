"""Multi-chip sharding for the placement solve.

The long axis of this workload is nodes (SURVEY.md §5: the (jobs x nodes)
matrix is our "long context"). The solve is embarrassingly parallel over
nodes except for one global reduction per placement step (the argmax over
node scores) and one scatter (the usage update on the winner) — exactly
the shape of ring-reduce workloads, so it rides ICI:

    mesh = Mesh(devices, ("nodes",))
    available, used, feasible, ...  sharded P("nodes")   [row-sharded]
    spread tables, ask, flags       replicated P()
    per-step: local scores -> global argmax (XLA all-reduce over ICI)
              -> one-hot usage update (local on the owning shard)

With jit + NamedSharding constraints XLA inserts the collectives; there
is no hand-written NCCL/MPI analog to port (the reference's comm backend
is msgpack-RPC/Serf/Raft, SURVEY.md §2.5 — control-plane replication
stays host-side, this module only distributes the math).

Used by __graft_entry__.dryrun_multichip and the multi-chip benchmarks.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def node_mesh(devices: Sequence = None, axis: str = "nodes") -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devs, (axis,))


def pad_node_axis(args: tuple, multiple: int) -> tuple:
    """Pad the node axis up to a multiple of the mesh size with infeasible
    dummy rows (available=0, feasible=False, spread_val_ok=False). The
    solve's argmax can never pick them, so choices stay valid indices into
    the real rows and scores are untouched — real clusters are rarely
    divisible by the device count."""
    n = args[0].shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return args
    args = list(args)

    def _pad(x, axis, value):
        x = np.asarray(x)
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return np.pad(x, widths, constant_values=value)

    args[0] = _pad(args[0], 0, 0)          # available
    args[1] = _pad(args[1], 0, 0)          # used0
    args[2] = _pad(args[2], 0, 0)          # placed_tg0
    args[3] = _pad(args[3], 0, 0)          # placed_job0
    args[5] = _pad(args[5], 0, False)      # feasible
    args[6] = _pad(args[6], 0, 0.0)        # affinity_boost
    args[7] = _pad(args[7], 0, 0.0)        # dev_affinity
    args[10] = _pad(args[10], 1, 0)        # spread_val_id
    args[11] = _pad(args[11], 1, False)    # spread_val_ok
    args[16] = _pad(args[16], 1, 0)        # dp_val_id
    args[17] = _pad(args[17], 1, False)    # dp_val_ok
    if len(args) > 25 and args[25] is not None:
        # tie_perm: dummy rows get the lowest priority, appended at the end
        args[25] = np.concatenate([
            np.asarray(args[25], np.int32), np.arange(n, n + pad, dtype=np.int32)])
    return tuple(args)


def shard_solve_args(mesh: Mesh, args: tuple, axis: str = "nodes"):
    """Device_put the solve_task_group argument tuple with node-axis rows
    sharded and everything else replicated. Pads the node axis to the
    mesh size first (see pad_node_axis).

    Argument order mirrors kernels.solve_task_group:
      0 available (N,D)   sharded   10 spread_val_id (S,N)  sharded ax1
      1 used0 (N,D)       sharded   11 spread_val_ok (S,N)  sharded ax1
      2 placed_tg0 (N,)   sharded   12 spread_counts0 (S,V) repl
      3 placed_job0 (N,)  sharded   13 spread_desired (S,V) repl
      4 ask (D,)          repl      14 spread_has_targets   repl
      5 feasible (N,)     sharded   15 spread_weight (S,)   repl
      6 affinity (N,)     sharded   16 dp_val_id (P,N)      sharded ax1
      7 dev_affinity (N,) sharded   17 dp_val_ok (P,N)      sharded ax1
      8 penalty_idx (K,)  repl      18 dp_counts0 (P,Vd)    repl
      9 active (K,)       repl      19 dp_limit (P,)        repl
                                    20..24 scalars          repl
                                    25 tie_perm (N,)        repl
    """
    args = pad_node_axis(args, int(np.prod(mesh.devices.shape)))
    specs = [
        P(axis, None), P(axis, None), P(axis), P(axis),
        P(), P(axis), P(axis), P(axis), P(), P(),
        P(None, axis), P(None, axis), P(), P(), P(), P(),
        P(None, axis), P(None, axis), P(), P(),
    ]
    specs += [P()] * (len(args) - len(specs))
    out = []
    for a, spec in zip(args, specs):
        out.append(a if a is None
                   else jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


def solve_task_group_sharded(mesh: Mesh, args: tuple, axis: str = "nodes"):
    """Run the placement solve with the node axis sharded over `mesh`.

    The same jitted kernel as the single-chip path: XLA propagates the
    input shardings through the scan and inserts ICI collectives for the
    global argmax each step. One collective PER PLACEMENT makes this
    latency-bound (round 4 measured it 7.3x slower than single-device at
    5K nodes) — it remains the general-semantics path (spread/
    distinct_hosts need per-placement rescoring), while the flagship
    bulk engine uses solve_bulk_multi_sharded below: one all-gather per
    EVAL, which is where the C2M scale lives.
    """
    from .kernels import solve_task_group

    sharded = shard_solve_args(mesh, args, axis)
    return solve_task_group(*sharded)


# --------------------------------------------------------------------------
# Sharded bulk engine (the C2M path on a mesh)
# --------------------------------------------------------------------------

def shard_bulk_state(mesh: Mesh, used0: np.ndarray, available: np.ndarray,
                     axis: str = "nodes"):
    """Device_put the bulk carry + capacity row-sharded over the mesh.
    The node axis must divide by the mesh size (ClusterStatic pads to a
    power of two, mesh sizes are powers of two)."""
    n_dev = int(np.prod(mesh.devices.shape))
    assert used0.shape[0] % n_dev == 0, (used0.shape, n_dev)
    sh = NamedSharding(mesh, P(axis, None))
    return (jax.device_put(np.asarray(used0, np.float32), sh),
            jax.device_put(np.asarray(available, np.float32), sh))


_STATE_SCATTER_CACHE: dict = {}


def make_state_scatter_sharded(mesh: Mesh, axis: str = "nodes",
                               donate: bool = True):
    """Row-sharded twin of the incremental state's delta scatter
    (tensor/incremental._scatter_fn): (used (N,D) sharded P(axis,None),
    idx (B,) replicated, delta (B,D) replicated) -> used with
    used[idx] += delta. Each shard masks off-shard rows to a zero delta
    and clips the index local — the same correction-fold idiom as
    _bulk_shard_body, so the result is bit-exact vs the single-device
    scatter (adds of integral f32 values commute exactly; a zero add is
    an exact no-op, usage rows are never -0.0). Jitted per (mesh,
    donate); donate=False is the solver's resync fold, which must keep
    the feed's twin alive behind the copy."""
    key = (mesh, axis, donate)
    fn = _STATE_SCATTER_CACHE.get(key)
    if fn is not None:
        return fn
    import jax.numpy as jnp

    smap = _shard_map_nocheck()

    def state_scatter_sharded(used, idx, delta):
        n_loc = used.shape[0]
        me = jax.lax.axis_index(axis)
        lo = me * n_loc
        local = idx - lo
        own = (local >= 0) & (local < n_loc)
        safe = jnp.clip(local, 0, n_loc - 1)
        return used.at[safe].add(jnp.where(own[:, None], delta, 0.0))

    body = smap(state_scatter_sharded, mesh=mesh,
                in_specs=(P(axis, None), P(), P()),
                out_specs=P(axis, None))
    fn = (jax.jit(body, donate_argnums=(0,)) if donate
          else jax.jit(body))
    _STATE_SCATTER_CACHE[key] = fn
    return fn


def _shard_map_nocheck():
    """shard_map with replication checking disabled under whichever
    keyword this jax spells it (check_rep was renamed check_vma)."""
    import inspect
    from functools import partial
    try:
        from jax import shard_map as _shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _shard_map
    _params = inspect.signature(_shard_map).parameters
    _nocheck = ({"check_vma": False} if "check_vma" in _params
                else {"check_rep": False} if "check_rep" in _params
                else {})
    return partial(_shard_map, **_nocheck)


def _bulk_shard_body(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta,
                     *, g: int, axis: str, n_dev: int, top_r: int):
    """Per-shard body of the distributed greedy bulk fill (the math of
    kernels._solve_bulk_multi_impl over row-sharded nodes). Module-level
    so the joint batch solver's shard body can inline it as the greedy
    arm of its portfolio — must run inside a shard_map over `axis`."""
    import jax.numpy as jnp

    from .kernels import NEG, TIE_JITTER, fit_scores

    n_loc, d = used0.shape
    n = n_loc * n_dev
    r = min(top_r, n_loc)
    me = jax.lax.axis_index(axis)
    lo = me * n_loc
    # fold usage corrections: global rows -> local rows, off-shard
    # slots masked to zero delta
    local = cidx - lo
    own = (local >= 0) & (local < n_loc)
    safe = jnp.clip(local, 0, n_loc - 1)
    used0 = jnp.maximum(
        used0.at[safe].add(
            jnp.where(own[:, None], cdelta, 0.0)), 0.0)

    def one_eval(used, gi):
        ask_g = ask[gi]
        ask_pos = ask_g > 0
        new_used = used + ask_g[None, :]
        ok = feas[gi] & jnp.all(new_used <= avail, axis=1)
        fitness = fit_scores(avail, new_used, False)
        aff_g = aff[gi]
        aff_present = aff_g != 0.0
        score = ((fitness + jnp.where(aff_present, aff_g, 0.0))
                 / (1.0 + aff_present.astype(jnp.float32)))
        score = jnp.where(ok, score, NEG)
        free = avail - used
        per_dim = jnp.where(
            ask_pos[None, :],
            jnp.floor(free / jnp.where(ask_pos, ask_g, 1.0)[None, :]),
            jnp.inf)
        cap = jnp.clip(jnp.min(per_dim, axis=1), 0, None)
        cap = jnp.where(score > NEG, cap, 0.0)
        budget0 = k[gi]
        cap = jnp.minimum(cap, budget0.astype(cap.dtype)).astype(
            jnp.int32)
        # same jitter stream as the single-device kernel, sliced to
        # this shard's rows (global (N,) generated then sliced so
        # the values per node agree across layouts)
        jit_all = jax.random.uniform(
            jax.random.PRNGKey(seeds[gi]), (n,), jnp.float32, 0.0,
            TIE_JITTER)
        key0 = score + jax.lax.dynamic_slice(jit_all, (lo,), (n_loc,))

        def round_body(state):
            take_loc, cap_loc, key_loc, budget, rnd, _ = state
            masked = jnp.where(cap_loc > 0, key_loc, NEG)
            vals, loc_idx = jax.lax.top_k(masked, r)
            pool = jnp.stack([
                vals,
                cap_loc[loc_idx].astype(jnp.float32),
                (loc_idx + lo).astype(jnp.float32),
            ])                                            # (3, R)
            pools = jax.lax.all_gather(pool, axis)        # (ndev,3,R)
            keys_all = pools[:, 0, :].reshape(-1)
            caps_all = pools[:, 1, :].reshape(-1).astype(jnp.int32)
            gidx_all = pools[:, 2, :].reshape(-1).astype(jnp.int32)
            # consume-safety threshold: worst pool entry of the
            # best-covered shard — anything above it beats every
            # node no shard surfaced this round
            thresh = jnp.max(pools[:, 0, r - 1])
            # keys desc, global index asc on ties (matches the
            # single-device stable argsort exactly)
            order = jnp.lexsort((gidx_all, -keys_all))
            keys_s = keys_all[order]
            caps_s = caps_all[order]
            eligible = keys_s > thresh
            # progress guarantee: the global best always consumes
            eligible = eligible.at[0].set(keys_s[0] > NEG)
            caps_e = jnp.where(eligible, caps_s, 0)
            cum = jnp.cumsum(caps_e).astype(jnp.int32)
            take_s = jnp.clip(budget - (cum - caps_e), 0, caps_e)
            # int32 pin: integer adds are associative, and the result
            # feeds the round-progress comparisons below
            consumed = jnp.sum(take_s, dtype=jnp.int32).astype(
                budget.dtype)
            # scatter back: mark eligible candidates consumed (cap
            # 0) and add takes on our own rows
            take_c = jnp.zeros_like(caps_all).at[order].set(take_s)
            elig_c = jnp.zeros(caps_all.shape, bool).at[order].set(
                eligible)
            pos = gidx_all - lo
            mine = (pos >= 0) & (pos < n_loc)
            posc = jnp.clip(pos, 0, n_loc - 1)
            take_loc = take_loc.at[posc].add(
                jnp.where(mine, take_c, 0))
            cap_loc = cap_loc.at[posc].multiply(
                jnp.where(mine & elig_c, 0, 1))
            budget = budget - consumed
            go = (budget > 0) & (keys_s[0] > NEG) & (consumed > 0)
            return take_loc, cap_loc, key_loc, budget, rnd + 1, go

        def round_cond(state):
            return state[5]

        init = (jnp.zeros(n_loc, jnp.int32), cap, key0, budget0,
                jnp.int32(0), budget0 > 0)
        take_loc, _, _, _, rnd, _ = jax.lax.while_loop(
            round_cond, round_body, init)
        used = used + ask_g[None, :] * take_loc[:, None].astype(
            used.dtype)
        # rnd == all-gathers this eval consumed (one per round); the
        # while state is replicated math so every shard reports the same
        # value — the launch's collective cadence, surfaced so the bench
        # can prove the one-gather-per-eval contract held at scale
        return used, (take_loc.astype(jnp.int16), rnd)

    used, (counts, rounds) = jax.lax.scan(one_eval, used0, jnp.arange(g))
    return used, counts, rounds


def make_solve_bulk_multi_sharded(mesh: Mesh, axis: str = "nodes",
                                  top_r: int = 64):
    """Build the mesh-sharded twin of kernels.solve_bulk_multi.

    Layout: capacity/carry/masks row-sharded over `axis`; asks/budgets
    replicated. Per eval, the fill runs as a short round loop of
    DISTRIBUTED top-k selection:

      round: each shard takes its local top-R candidates by jittered
             score (local compute, no collective) -> ONE tiled
             all-gather of the (R,) keys/caps/ids per shard -> every
             device merges the <= R*n_dev candidates (a tiny sort) and
             consumes, in global key order, every candidate whose key
             beats the WORST pool entry of every shard (those provably
             outrank all unseen nodes) until the budget is filled ->
             each shard applies its own slice of the usage update.

    Fill-to-capacity means the number of consuming rounds is
    ~touched_nodes / (R * n_dev) — almost always 1 — so the collective
    cadence is O(G) tiny gathers per launch, vs O(K) global argmaxes
    for the per-placement scan (round 4's 7.3x sharded slowdown), and
    no step replicates O(N log N) sort work. Tie-breaks are the same
    additive score jitter as the single-device kernel; counts agree
    exactly with kernels.solve_bulk_multi.

    Returns solve(used0_sharded, avail_sharded, feas, aff, ask, k,
    seeds, cidx, cdelta, *, g) -> (new_used sharded, (G, N) int16
    counts sharded on the node axis, (G,) int32 replicated all-gather
    rounds per eval — the launch's collective cadence).
    """
    from functools import partial

    shard_map = _shard_map_nocheck()
    n_dev = int(np.prod(mesh.devices.shape))

    @partial(jax.jit, static_argnames=("g",), donate_argnums=(0,))
    def solve(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta, *,
              g: int):
        fn = shard_map(
            partial(_bulk_shard_body, g=g, axis=axis, n_dev=n_dev,
                    top_r=top_r),
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None), P(None, axis),
                      P(None, axis), P(), P(), P(), P(), P()),
            out_specs=(P(axis, None), P(None, axis), P()))
        return fn(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta)

    return solve


def make_solve_batch_sharded(mesh: Mesh, axis: str = "nodes",
                             top_r: int = 64):
    """Build the mesh-sharded twin of batch_solver.solve_batch (the
    "tpu-solve" joint auction over a whole eval batch).

    Layout matches make_solve_bulk_multi_sharded: carry/capacity
    row-sharded, per-eval masks column-sharded, asks/budgets replicated.
    Per AUCTION ROUND (not per eval, not per placement):

      each shard computes its local (G, n_loc) bid matrix and its local
      top-R candidates per eval (bid, capacity, global node id) -> ONE
      all-gather of the (3, G, R) pools -> every device merges them
      into each eval's EXACT global top-R (value desc, node id asc —
      the same order single-device top_k yields, so counts agree
      bit-exactly across layouts), resolves per-node winners and the
      winners' score-ordered capacity fills over the <= G*R candidates
      (replicated small-matrix work) -> each shard applies the usage
      updates for the rows it owns; the price vector stays replicated.

    So the collective cadence is one small all-gather per round, and
    rounds converge in a handful (~touched_nodes / TOP_R, see
    batch_solver.MAX_ROUNDS) — independent of both K and G, vs O(G)
    gathers for the sharded greedy chain. The greedy arm of the
    portfolio reuses _bulk_shard_body inside the SAME shard_map, and
    the arm-selection scores reduce with one psum each.

    Returns solve(used0_sharded, avail_sharded, feas, aff, ask, k,
    seeds, cidx, cdelta, *, g) -> (new_used sharded, (G, N) int16
    counts sharded on the node axis, (6,) f32 replicated info row with
    the same layout as batch_solver.solve_batch, plus a replicated
    int32 scalar counting the launch's all-gathers across every
    portfolio arm and the greedy chain).
    """
    import jax.numpy as jnp
    from functools import partial

    from .batch_solver import (MAX_ROUNDS, PORTFOLIO, PRICE_EPS, TOP_R,
                               _pairwise_sum_xp)
    from .kernels import NEG, TIE_JITTER

    shard_map = _shard_map_nocheck()
    n_dev = int(np.prod(mesh.devices.shape))

    def _joint_body(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta,
                    evict=None, net_prio=None, *, g: int):
        from .kernels import _fit_scores_xp as fit_xp

        n_loc, d = used0.shape
        n = n_loc * n_dev
        f = used0.dtype
        me = jax.lax.axis_index(axis)
        lo = me * n_loc
        # victim budgets (row-sharded like avail); pscore is local too
        avail_cap = avail if evict is None else avail + evict
        pscore_loc = (None if net_prio is None else
                      1.0 / (1.0 + jnp.exp(0.0048 * (net_prio - 2048.0))))
        # int32 throughout the carry (x64 mode: arange defaults int64,
        # sum() promotes int32 -> int64 — both break the loop carry)
        g_idx = jnp.arange(g, dtype=jnp.int32)
        # fold corrections (global rows -> local), as the bulk body does
        local = cidx - lo
        own = (local >= 0) & (local < n_loc)
        safe = jnp.clip(local, 0, n_loc - 1)
        used0 = jnp.maximum(
            used0.at[safe].add(jnp.where(own[:, None], cdelta, 0.0)), 0.0)

        # greedy arm: the distributed bulk fill from the same start
        # state (corrections already folded -> no-op slots)
        used_g, counts_g, rounds_g = _bulk_shard_body(
            used0, avail, feas, aff, ask, k, seeds,
            jnp.zeros(1, jnp.int32), jnp.zeros((1, d), f),
            g=g, axis=axis, n_dev=n_dev, top_r=top_r)
        # collective cadence of the whole launch: the greedy arm's
        # per-eval gathers plus one gather per auction round per
        # portfolio restart (accumulated below) — replicated math
        gathers = jnp.sum(rounds_g)

        ask_pos = ask > 0
        aff_present = aff != 0.0
        divisor = 1.0 + aff_present.astype(f)

        r_loc = min(TOP_R, n_loc)
        r_glob = min(TOP_R, n)

        def body(state, jits, price_eps):
            used, remaining, take, price, rnd, _ = state
            price_loc = jax.lax.dynamic_slice(price, (lo,), (n_loc,))
            new_used = used[None, :, :] + ask[:, None, :]     # (G,nl,D)
            ok = feas & jnp.all(new_used <= avail_cap[None, :, :], axis=2)
            ok &= (remaining > 0)[:, None]
            if evict is None:
                fitness = fit_xp(jnp, avail[None, :, :], new_used, False)
                score = (fitness
                         + jnp.where(aff_present, aff, 0.0)) / divisor
            else:
                # over-capacity bids spend victim budget (mirrors the
                # single-device eviction branch exactly)
                fitness = fit_xp(
                    jnp, avail[None, :, :],
                    jnp.minimum(new_used, avail[None, :, :]), False)
                over = jnp.any(new_used > avail[None, :, :], axis=2)
                score = (fitness + jnp.where(aff_present, aff, 0.0)
                         + jnp.where(over, pscore_loc[None, :], 0.0)) / (
                             divisor + over.astype(f))
            bid = jnp.where(ok, score + jits - price_loc[None, :], NEG)
            lvals, lidx = jax.lax.top_k(bid, r_loc)           # (G, RL)
            free = avail_cap[lidx] - used[lidx]               # (G,RL,D)
            per_dim = jnp.where(
                ask_pos[:, None, :],
                jnp.floor(free
                          / jnp.where(ask_pos, ask, 1.0)[:, None, :]),
                jnp.inf)
            lcap = jnp.clip(jnp.min(per_dim, axis=2), 0, None)
            pool = jnp.stack([
                lvals, lcap.astype(jnp.float32),
                (lidx + lo).astype(jnp.float32)])             # (3,G,RL)
            pools = jax.lax.all_gather(pool, axis)          # (ndev,3,G,RL)
            vals_m = pools[:, 0].transpose(1, 0, 2).reshape(g, -1)
            caps_m = pools[:, 1].transpose(1, 0, 2).reshape(g, -1)
            gids_m = pools[:, 2].transpose(1, 0, 2).reshape(g, -1)
            # merge to each eval's EXACT global top-R, ordered (value
            # desc, node id asc) — what single-device top_k over the
            # full row yields, so every layout sees the same candidates
            neg_s, gid_s, cap_s = jax.lax.sort(
                (-vals_m, gids_m, caps_m), dimension=1, num_keys=2)
            vals = -neg_s[:, :r_glob]                         # (G, R)
            gids = gid_s[:, :r_glob].astype(jnp.int32)
            caps = cap_s[:, :r_glob]
            active = vals > NEG / 2
            flat_gid = gids.reshape(-1)
            flat_val = jnp.where(active, vals, NEG).reshape(-1)
            flat_g = jnp.broadcast_to(
                g_idx[:, None], gids.shape).reshape(-1)
            # winner per node among all surfaced candidates — the
            # (N,)-sized boards stay replicated (same math every shard)
            node_best = jnp.full(n, NEG, f).at[flat_gid].max(flat_val)
            is_best = ((flat_val > NEG / 2)
                       & (flat_val >= node_best[flat_gid]))
            node_winner = jnp.full(n, g, jnp.int32).at[flat_gid].min(
                jnp.where(is_best, flat_g, g))
            won = active & (vals >= node_best[gids]) & (
                node_winner[gids] == g_idx[:, None])          # (G, R)
            cap_w = jnp.where(won, caps, 0.0)
            # spend remaining demand across won nodes in score order
            prefix = jnp.cumsum(cap_w, axis=1) - cap_w
            amt = jnp.clip(remaining.astype(f)[:, None] - prefix,
                           0.0, cap_w).astype(jnp.int32)      # (G, R)
            # each shard applies the rows it owns
            pos = gids - lo
            mine = (pos >= 0) & (pos < n_loc)
            posc = jnp.clip(pos, 0, n_loc - 1)
            amt_mine = jnp.where(mine, amt, 0)
            used = used.at[posc.reshape(-1)].add(
                (ask[:, None, :] * amt_mine[..., None].astype(f)
                 ).reshape(-1, d))
            take = take.at[g_idx[:, None], posc].add(amt_mine)
            remaining = remaining - amt.sum(
                axis=1, dtype=jnp.int32)             # replicated math
            # exhaustion-gated price bump, replicated math (see the
            # single-device body for why contested alone is not enough)
            bids_per_node = jnp.zeros(n, jnp.int32).at[flat_gid].add(
                active.reshape(-1).astype(jnp.int32))
            filled = won & (cap_w > 0) & (amt.astype(f) >= cap_w)
            node_filled = jnp.zeros(n, jnp.bool_).at[flat_gid].max(
                filled.reshape(-1))
            price = price + price_eps * (
                node_filled & (bids_per_node > 1)).astype(f)
            return (used, remaining, take, price, rnd + 1,
                    jnp.any(amt > 0))

        def cond(state):
            _, remaining, _, _, rnd, progressed = state
            return ((rnd < MAX_ROUNDS) & progressed
                    & jnp.any(remaining > 0))

        # auction arm: one run per PORTFOLIO (jitter_scale, price_temp)
        # entry with fresh tie-break jitter each time (same fold_in
        # stream as the single-device kernel, global (N,) generated then
        # sliced so values per node agree across layouts); selection
        # chain mirrors batch_solver.solve_batch exactly — earliest
        # restart wins exact ties — so counts stay bit-identical to the
        # single-device path
        def det_score(take2d, used_loc):
            # bit-identical to the single-device _packing_score_xp:
            # gather the per-node contributions and reduce over the
            # GLOBAL node order with the same fixed pairwise tree. A
            # psum of per-shard partial sums reassociates the float
            # adds per mesh size, and a one-ulp score wobble is enough
            # to flip a near-tied portfolio selection — breaking
            # cross-mesh count parity
            contrib = (take2d.sum(axis=0).astype(f)
                       * fit_xp(jnp, avail, used_loc, False))  # (n_loc,)
            return _pairwise_sum_xp(
                jnp, jax.lax.all_gather(contrib, axis).reshape(-1))

        used_a = take = rnd = None
        score_a = placed_a = None
        for t, (jscale, ptemp) in enumerate(PORTFOLIO):
            jits = jax.vmap(lambda s, _t=t, _js=jscale: jax.lax.dynamic_slice(
                jax.random.uniform(
                    jax.random.fold_in(jax.random.PRNGKey(s), _t), (n,),
                    jnp.float32, 0.0, TIE_JITTER * _js),
                (lo,), (n_loc,)))(seeds)
            init = (used0, k.astype(jnp.int32),
                    jnp.zeros((g, n_loc), jnp.int32), jnp.zeros(n, f),
                    jnp.int32(0), jnp.bool_(True))
            used_t, _, take_t, _, rnd_t, _ = jax.lax.while_loop(
                cond, lambda st, j=jits, pe=PRICE_EPS * ptemp:
                body(st, j, pe), init)
            # +1 for the det_score gather (placed stays a psum: integer
            # adds are associative, so it cannot wobble)
            gathers = gathers + rnd_t + 1
            placed_t = jax.lax.psum(take_t.sum(dtype=jnp.int32), axis)
            score_t = det_score(take_t, used_t)
            if t == 0:
                used_a, take, rnd = used_t, take_t, rnd_t
                score_a, placed_a = score_t, placed_t
            else:
                better = (placed_t > placed_a) | (
                    (placed_t == placed_a) & (score_t > score_a))
                used_a = jnp.where(better, used_t, used_a)
                take = jnp.where(better, take_t, take)
                rnd = jnp.where(better, rnd_t, rnd)
                score_a = jnp.where(better, score_t, score_a)
                placed_a = jnp.where(better, placed_t, placed_a)

        # portfolio selection vs greedy on globally-reduced scores
        placed_g = jax.lax.psum(counts_g.astype(jnp.int32).sum(), axis)
        score_g = det_score(counts_g.astype(jnp.int32), used_g)
        gathers = gathers + 1
        pick_a = (placed_a > placed_g) | (
            (placed_a == placed_g) & (score_a > score_g))
        used = jnp.where(pick_a, used_a, used_g)
        counts = jnp.where(pick_a, take.astype(jnp.int16), counts_g)
        info = jnp.stack([
            score_a.astype(jnp.float32), score_g.astype(jnp.float32),
            placed_a.astype(jnp.float32), placed_g.astype(jnp.float32),
            rnd.astype(jnp.float32), pick_a.astype(jnp.float32)])
        return used, counts, info, gathers

    @partial(jax.jit, static_argnames=("g",), donate_argnums=(0,))
    def solve(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta,
              evict=None, net_prio=None, *, g: int):
        base_specs = (P(axis, None), P(axis, None), P(None, axis),
                      P(None, axis), P(), P(), P(), P(), P())
        out = (P(axis, None), P(None, axis), P(), P())
        if evict is None:
            fn = shard_map(
                partial(_joint_body, g=g), mesh=mesh,
                in_specs=base_specs, out_specs=out)
            return fn(used0, avail, feas, aff, ask, k, seeds, cidx,
                      cdelta)
        # victim budgets ride the node axis like avail; net_prio is a
        # plain (N,) node row
        fn = shard_map(
            partial(_joint_body, g=g), mesh=mesh,
            in_specs=base_specs + (P(axis, None), P(axis)),
            out_specs=out)
        return fn(used0, avail, feas, aff, ask, k, seeds, cidx, cdelta,
                  evict, net_prio)

    return solve
