"""Perf-correctness guard for the jitted solver hot path.

Two silent performance killers on a warm solver:

- **retraces**: shape/dtype/weak-type drift recompiles a jitted
  function that was supposed to be warm, billing an XLA compile (tens
  of ms to seconds) to a production launch;
- **implicit host transfers**: a numpy array slipping into a launch (or
  a device array silently read back) ships bytes synchronously on every
  call.

``cache_size()`` probes a jitted function's compile-cache entry count
(the ``_cache_size`` hook on JAX's jit wrapper). ``no_retrace()`` turns
a code region into a hard window: any implicit transfer raises
immediately (``jax.transfer_guard("disallow")`` — explicit
``jax.device_put``/``jax.device_get`` stay legal), and on exit the
wrapped functions' caches must not have grown beyond ``expect``
compiles. The BulkSolverService wraps every non-sharded launch in a
window and folds the deltas into ``stats["compiles"]`` /
``stats["retraces"]`` so the tests (and any operator reading
/v1/agent/solver stats) can assert a warm steady state.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterator


class RetraceError(AssertionError):
    """A jit cache grew inside a window that promised it would not."""


def cache_size(fn) -> int:
    """Number of compiled entries behind a jitted callable, or -1 when
    the wrapper exposes no probe (non-jitted callable, API drift)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return -1
    try:
        return int(probe())
    except Exception:
        return -1


@contextlib.contextmanager
def no_retrace(*fns, expect: int = 0) -> Iterator[Dict]:
    """Hard perf window: implicit host<->device transfers raise, and
    each fn in ``fns`` may gain at most ``expect`` new compile-cache
    entries (0 = must already be warm). Yields a dict whose "compiles"
    key holds the total cache growth observed on exit."""
    import jax

    before = [(fn, cache_size(fn)) for fn in fns]
    out: Dict = {"compiles": 0}
    with jax.transfer_guard("disallow"):
        try:
            yield out
        except Exception as e:
            # attribute the trip to the launch ledger (NOMAD_TPU_SAN=1)
            # before re-raising: the guard is the enforcement point, the
            # ledger is the attribution record
            if "transfer" in str(e).lower():
                from ..analysis import launch_ledger
                launch_ledger.note_unsanctioned(
                    f"a no_retrace window over "
                    f"{[getattr(f, '__name__', str(f)) for f in fns]}")
            raise
    grew = []
    for fn, b in before:
        a = cache_size(fn)
        if b < 0 or a < 0:
            continue
        out["compiles"] += max(0, a - b)
        if a - b > expect:
            grew.append(f"{getattr(fn, '__name__', fn)}: {b} -> {a}")
    if grew:
        raise RetraceError(
            "jit cache grew past the promised warmup inside a "
            f"no_retrace window ({'; '.join(grew)}): an argument's "
            "shape/dtype/weak-type drifted on the hot path")


@contextlib.contextmanager
def count_compiles(*fns) -> Iterator[Dict]:
    """Soft variant for warmup accounting: no transfer guard, no limit;
    yields a dict whose "compiles" key is filled on exit."""
    before = [(fn, cache_size(fn)) for fn in fns]
    out: Dict = {"compiles": 0}
    yield out
    for fn, b in before:
        a = cache_size(fn)
        if b >= 0 and a >= 0:
            out["compiles"] += max(0, a - b)
