"""nomadstate: device-resident incremental cluster state.

Every scheduling round used to rebuild the (N, D) usage tensor from a
host snapshot — an O(N) gather + device_put per eval that at C2M scale
is the wall after the solver's solve/apply overlap. This module makes
the warm-path tensor build O(allocs changed) instead: one
:class:`IncrementalFeed` per store subscribes to the commit stream's
Allocation/Node topics (the same contract ``analysis/shadow.py``'s
sanitizer machine-checks) and folds each delta into a persistent host
base plus a compact device-delta log, so

- ``ClusterTensors.refresh_usage`` takes the fed base as a shared
  read-only view (zero per-round host work) instead of re-gathering
  the store's usage matrix;
- the bulk solver service's resync takes a device-RESIDENT twin of the
  base (sharded ``NamedSharding(P("nodes", None))``, same layout as
  the solve carry) and folds its open-ledger entries with ONE jitted
  scatter-add launch instead of shipping a rebuilt O(N) host array.

Delta-folding semantics are ``state/deltas.py``'s — the single
implementation shared with the shadow sanitizer: columnar AllocBlock
expansion (held by reference here, never expanded to per-position
rows), promoted-row override, GC pops, truncation→resync. The feed is
PULL-model: deltas drain at build/verify time under the feed's own
lock, never on the store's commit path, so event consumption costs the
scheduler nothing until it needs fresh state.

Consistency contract (the part chaos + NOMAD_TPU_SAN=1 enforce):

- RESYNC rebuilds from one MVCC snapshot — base rows from the
  gen-bounded ``_node_usage`` table, row/block bookkeeping from
  gen-bounded table iteration — and pins ``position = snap.index``.
  Any drained event with ``index <= position`` is already inside the
  base and is discarded; events beyond it fold incrementally. Ring
  truncation, the ``restore`` sentinel, node deletion, and any parity
  mismatch all route back through this path: resync is the repair
  story, never incremental patching.
- PARITY: every K builds under ``NOMAD_TPU_SAN=1`` (and on demand from
  the chaos invariant sweep / the state smoke) the feed drains to a
  write-lock-consistent index and digests its base — device twins
  included — against a fresh rebuild from the same gen-bounded tables.
  Resource vectors are integral, so f64 folds commute exactly and the
  compare demands bit-equality, no tolerance.
- ``NOMAD_TPU_INCR=0`` kills the feature at every call site: builds
  fall back to the exact prior per-round rebuild (the feed still
  drains lazily, it just hands nothing out).

The shared base view is refreshed in place by later drains, so a solve
that kept the view may observe newer committed usage mid-read — the
same freshness the legacy ``_usage_mat`` gather already leaks by
design; the serialized plan applier owns correctness either way.
"""

from __future__ import annotations

import _thread
import os
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..state.deltas import ALLOC_ROW_KINDS
from ..structs.resources import RESOURCE_DIMS

_REAL_LOCK = _thread.allocate_lock

FEED_TOPICS = {"Allocation": ["*"], "Node": ["*"]}

# builds between base-vs-rebuild parity digests when the sanitizer is on
PARITY_EVERY = 64
# device-delta batches pad to powers of two from this floor so the warm
# path cycles a handful of compiled scatter shapes
SCATTER_FLOOR = 8
# a twin lagging more than one full base behind re-uploads instead of
# scattering; a log grown past this multiple drops every twin and resets
LOG_CAP_MULT = 4

# shapes already compiled for the delta scatter / resync fold launches
# (tensor/solver.warm_launch discipline: warm shapes compile nothing)
_STATE_WARM: set = set()


def incr_enabled() -> bool:
    """Kill switch, read at call time so tests can flip it per-case."""
    return os.environ.get("NOMAD_TPU_INCR", "1") != "0"


def _pad_bucket(n: int) -> int:
    out = SCATTER_FLOOR
    while out < n:
        out *= 2
    return out


# -- jitted scatter (single-device arm; the sharded twin lives in
#    tensor/sharding.make_state_scatter_sharded) -------------------------

_SCATTER_JIT = None


def _scatter_fn(donate: bool):
    """used.at[idx].add(delta): ONE launch applies a whole delta batch.
    Padding rows carry (idx=0, delta=0) — an exact no-op add (usage
    values are integral and never -0.0)."""
    global _SCATTER_JIT
    if _SCATTER_JIT is None:
        import jax

        def state_scatter(used, idx, delta):
            return used.at[idx].add(delta)

        def state_fold(used, idx, delta):
            return used.at[idx].add(delta)

        _SCATTER_JIT = (jax.jit(state_scatter, donate_argnums=(0,)),
                        jax.jit(state_fold))
    return _SCATTER_JIT[0 if donate else 1]


class Violation:
    __slots__ = ("kind", "message")

    def __init__(self, kind: str, message: str):
        self.kind = kind
        self.message = message

    def render(self) -> str:
        return f"[{self.kind}] {self.message}"


class _Twin:
    """One device-resident f32 copy of the base (per placement layout:
    single-device, or one per mesh), caught up to `cursor` entries of
    the epoch's delta log."""

    __slots__ = ("arr", "cursor")

    def __init__(self, arr, cursor: int):
        self.arr = arr
        self.cursor = cursor


class _Epoch:
    """Feed state bound to one node LAYOUT (ordered id tuple). A static
    version bump with identical membership keeps the epoch — content
    edits don't move usage rows; membership changes resync."""

    __slots__ = ("layout", "node_index", "n_pad", "base", "base_view",
                 "position", "rows", "blocks", "gc_dropped", "stale",
                 "devlog", "twins", "static_ref")

    def __init__(self, layout: tuple, node_index: Dict[str, int],
                 n_pad: int, position: int):
        self.layout = layout
        self.node_index = node_index
        self.n_pad = n_pad
        self.base = np.zeros((n_pad, RESOURCE_DIMS))
        self.base_view = self.base.view()
        self.base_view.setflags(write=False)
        self.position = position
        # alloc id -> (node_id, counted, vec) for REAL rows only; block
        # positions stay columnar (virtual prev computed on demand)
        self.rows: Dict[str, tuple] = {}
        self.blocks: Dict[str, object] = {}
        # per block id: positions GC'd after our held (insert-time) ref
        self.gc_dropped: Dict[str, Set[int]] = {}
        self.stale = False
        # append-only (row, f64 delta vec) log the device twins consume
        self.devlog: List[Tuple[int, np.ndarray]] = []
        self.twins: Dict[object, _Twin] = {}
        self.static_ref = None


class IncrementalFeed:
    """Delta-fed usage state for one (store, broker) pair. All entry
    points take ``self._lock``; nothing here runs on the commit path."""

    def __init__(self, store, broker, tracker: "StateTracker"):
        self.store = store
        self.tracker = tracker
        self.sub = broker.subscribe(dict(FEED_TOPICS))
        self._lock = _REAL_LOCK()
        self._epoch: Optional[_Epoch] = None
        self._builds = 0
        self._fast_hits = 0
        self._resyncs = 0
        self._deltas_applied = 0
        self._parity_checks = 0
        self._alloc_uncounted = 0
        self._gauge_pub = None

    # -- public surface ------------------------------------------------

    def base_for(self, static) -> Optional[np.ndarray]:
        """The fed usage base aligned to `static`'s row order, as a
        read-only (n_pad, D) f64 view — or None (kill switch off, or
        resync failed), which means: do the legacy full build."""
        if not incr_enabled() or static is None:
            return None
        with self._lock:
            self._builds += 1
            ep = self._epoch_for_locked(static)
            if ep is None:
                return None
            self._fast_hits += 1
            if (self.tracker.san_active
                    and self._builds % PARITY_EVERY == 0):
                self._verify_locked()
                ep = self._epoch
                if ep is None or ep.stale:
                    return None
            self._gauges()
            return ep.base_view

    def device_used(self, static, mesh=None):
        """Device-resident f32 twin of the base (sharded over `mesh`
        when given), flushed through one scatter launch. None when the
        feed can't serve this static — caller falls back to host."""
        if not incr_enabled() or static is None:
            return None
        with self._lock:
            ep = self._epoch_for_locked(static)
            if ep is None:
                return None
            return self._twin_locked(ep, mesh).arr

    def take_build_delta_count(self) -> int:
        """Exact Allocation-delta count since the previous take — the
        per-build number the changed_allocs_per_build histogram wants.
        Drains first so queued deltas land in THIS build's bucket."""
        with self._lock:
            ep = self._epoch
            if ep is not None and not ep.stale:
                self._drain_locked(ep)
            out, self._alloc_uncounted = self._alloc_uncounted, 0
            return out

    def force_verify(self) -> bool:
        """Drain + parity-digest now (chaos sweep, state smoke,
        teardowns). Builds an epoch over the store's node set first if
        none exists, so follower replicas verify meaningfully."""
        if not incr_enabled():
            return True
        with self._lock:
            if self._epoch is None or self._epoch.stale:
                snap = self.store.snapshot()
                try:
                    ids = sorted(n.id for n in snap.nodes())
                finally:
                    snap.close()
                layout = tuple(ids)
                index = {nid: i for i, nid in enumerate(ids)}
                n_pad = _pad_pow2(max(len(ids), 1))
                if not self._resync_locked(layout, index, n_pad):
                    return True     # nothing to verify against
            return self._verify_locked()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "builds": self._builds,
                "fast_hits": self._fast_hits,
                "resyncs": self._resyncs,
                "deltas_applied": self._deltas_applied,
                "parity_checks": self._parity_checks,
            }

    # -- epoch lifecycle ----------------------------------------------

    def _epoch_for_locked(self, static) -> Optional[_Epoch]:
        ep = self._epoch
        if ep is not None and not ep.stale:
            if ep.static_ref is static:
                self._drain_locked(ep)
                ep = self._epoch          # drain may have resynced
            elif ep.layout == tuple(static.node_index):
                # version bump, same membership/order: adopt the new
                # static, keep the base (usage rows didn't move)
                ep.static_ref = static
                ep.node_index = static.node_index
                self._drain_locked(ep)
                ep = self._epoch
            else:
                ep = None
        if ep is None or ep.stale:
            layout = tuple(static.node_index)
            if not self._resync_locked(layout, static.node_index,
                                       static.n_pad):
                return None
            ep = self._epoch
            ep.static_ref = static
        return ep

    def _resync_locked(self, layout: tuple, node_index: Dict[str, int],
                       n_pad: int) -> bool:
        """Rebuild everything from one MVCC snapshot. Every event with
        index <= snap.index is inside the rebuilt base; the global
        discard-by-position rule in _drain_locked makes that airtight
        regardless of commit-listener interleaving."""
        # discard the backlog first: all of it predates the snapshot we
        # are about to take, so all of it is (or will be) in the base
        evs = self.sub.next_events(timeout=0)
        self.sub.truncated = False
        self._alloc_uncounted += sum(1 for e in evs
                                     if e.topic == "Allocation")
        store = self.store
        snap = store.snapshot()
        try:
            ep = _Epoch(layout, node_index, n_pad, snap.index)
            gen = snap.index
            usage = store._node_usage
            for nid, i in node_index.items():
                vec = usage.get(nid, gen)
                if vec is not None:
                    ep.base[i] = vec
            for aid, a in store._allocs.iterate(gen):
                ep.rows[aid] = (a.node_id, not a.terminal_status(),
                                a.allocated_vec)
            for bid, block in store._alloc_blocks.iterate(gen):
                ep.blocks[bid] = block
        except Exception:
            self._epoch = None
            return False
        finally:
            snap.close()
        self._epoch = ep
        self._resyncs += 1
        self._gauges()
        return True

    # -- drain + fold --------------------------------------------------

    def _drain_locked(self, ep: _Epoch) -> None:
        evs = self.sub.next_events(timeout=0)
        if self.sub.truncated:
            # lapped ring or restore sentinel: the contract answer is a
            # full resync, never incremental patching
            self.sub.truncated = False
            self._resync_locked(ep.layout, ep.node_index, ep.n_pad)
            if self._epoch is not None:
                self._epoch.static_ref = ep.static_ref
            return
        for e in evs:
            if e.topic == "Allocation":
                self._alloc_uncounted += 1
            if e.index <= ep.position:
                continue        # already inside the resync base
            self._fold(ep, e)
        # ep.position is the resync FLOOR, never advanced per event:
        # one commit emits many events sharing one index (and a drain
        # can catch a commit's topic shards half-published), so
        # advancing on the first would discard its siblings. Delivery
        # past the floor is exactly-once by the subscription cursor.

    def _fold(self, ep: _Epoch, e) -> None:
        kind = e.type
        p = e.payload
        if kind in ALLOC_ROW_KINDS:
            self._fold_alloc_row(ep, p)
        elif kind == "alloc-block-upsert":
            self._fold_block(ep, p)
        elif kind == "alloc-gc":
            self._fold_gc(ep, p)
        elif kind == "node-delete":
            if p is not None and p.id in ep.node_index:
                # membership changed mid-epoch; the next build's static
                # carries the new layout — serve nothing until then
                ep.stale = True
        # other NODE_KINDS: content-only, usage rows don't move

    def _fold_alloc_row(self, ep: _Epoch, a) -> None:
        new = (a.node_id, not a.terminal_status(), a.allocated_vec)
        prev = ep.rows.get(a.id)
        if prev is None:
            prev = self._virtual_row(ep, a.id)
        ep.rows[a.id] = new
        if prev is not None:
            pn, pc, pv = prev
            if (pc and new[1] and pn == new[0] and pv is not None
                    and new[2] is not None
                    and np.array_equal(pv, new[2])):
                return          # annotation-only rewrite (store predicate)
            if pc and pv is not None:
                self._add(ep, pn, pv, -1.0)
        if new[1] and new[2] is not None:
            self._add(ep, new[0], new[2], 1.0)

    def _fold_block(self, ep: _Epoch, block) -> None:
        if block.id in ep.blocks:
            ep.blocks[block.id] = block     # defensive; store emits once
            return
        ep.blocks[block.id] = block
        vec = block.allocated_vec
        for m in block.live_rows():
            c = int(block.counts[m])
            self._add(ep, block.node_ids[m],
                      vec * c if c != 1 else vec, 1.0)

    def _fold_gc(self, ep: _Epoch, ids) -> None:
        from ..structs.alloc import BLOCK_SEP
        for aid in ids:
            # every gcable alloc is terminal → never usage-counting: GC
            # pops bookkeeping, moves no resources (store contract)
            ep.rows.pop(aid, None)
            sep = aid.rfind(BLOCK_SEP)
            if sep > 0:
                try:
                    pos = int(aid[sep + 1:])
                except ValueError:
                    continue
                ep.gc_dropped.setdefault(aid[:sep], set()).add(pos)

    def _virtual_row(self, ep: _Epoch, aid: str) -> Optional[tuple]:
        """A block position's implied row — the feed-side mirror of
        store._block_alloc_fallback over our held (insert-time) block
        ref, with gc_dropped compensating for the store's quiet
        with_dropped re-puts."""
        from ..structs.alloc import BLOCK_SEP
        sep = aid.rfind(BLOCK_SEP)
        if sep < 0:
            return None
        block = ep.blocks.get(aid[:sep])
        if block is None:
            return None
        try:
            pos = int(aid[sep + 1:])
        except ValueError:
            return None
        if pos < 0 or pos >= block.size or not block.visible(pos):
            return None
        if pos in ep.gc_dropped.get(aid[:sep], ()):
            return None
        m = block.row_for_pos(pos)
        return (block.node_ids[m], True, block.allocated_vec)

    def _add(self, ep: _Epoch, node_id: str, vec, sign: float) -> None:
        row = ep.node_index.get(node_id)
        if row is None:
            return
        delta = vec[:RESOURCE_DIMS] if sign > 0 else -vec[:RESOURCE_DIMS]
        ep.base[row] += delta
        self._deltas_applied += 1
        if ep.twins:
            ep.devlog.append((row, delta))
            if len(ep.devlog) > LOG_CAP_MULT * ep.n_pad:
                # runaway log with no consumer draining it: cheaper to
                # re-upload the base than to replay this much
                ep.devlog.clear()
                ep.twins.clear()

    # -- device twins --------------------------------------------------

    def _twin_locked(self, ep: _Epoch, mesh) -> _Twin:
        import jax

        key = mesh if mesh is not None else None
        tw = ep.twins.get(key)
        if tw is not None and len(ep.devlog) - tw.cursor > ep.n_pad:
            tw = None               # lagged past a full base: re-upload
        if tw is None:
            arr = np.ascontiguousarray(ep.base, dtype=np.float32)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                arr = jax.device_put(
                    arr, NamedSharding(mesh, P("nodes", None)))
            else:
                arr = jax.device_put(arr)
            tw = ep.twins[key] = _Twin(arr, len(ep.devlog))
        elif tw.cursor < len(ep.devlog):
            tw.arr = self._flush_twin(ep, tw, mesh)
            tw.cursor = len(ep.devlog)
        if all(t.cursor == len(ep.devlog) for t in ep.twins.values()):
            for t in ep.twins.values():
                t.cursor = 0
            ep.devlog.clear()
        return tw

    def _flush_twin(self, ep: _Epoch, tw: _Twin, mesh):
        """ONE donated scatter launch applies every pending delta to
        this twin. Pad rows (idx 0, delta 0) are exact no-ops."""
        import jax

        from .solver import warm_launch

        entries = ep.devlog[tw.cursor:]
        bucket = _pad_bucket(len(entries))
        d = RESOURCE_DIMS
        idx = np.zeros(bucket, dtype=np.int32)
        delta = np.zeros((bucket, d), dtype=np.float32)
        for i, (row, vec) in enumerate(entries):
            idx[i] = row
            delta[i] = vec
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .sharding import make_state_scatter_sharded

            n_dev = int(np.prod(mesh.devices.shape))
            fn = make_state_scatter_sharded(mesh)
            rep = NamedSharding(mesh, P())
            idx = jax.device_put(idx, rep)
            delta = jax.device_put(delta, rep)
            key = ("statescatter-sh", ep.n_pad, d, bucket, n_dev)
        else:
            fn = _scatter_fn(donate=True)
            idx, delta = jax.device_put((idx, delta))
            key = ("statescatter", ep.n_pad, d, bucket)
        with warm_launch(fn, key, _STATE_WARM):
            return fn(tw.arr, idx, delta)

    # -- parity --------------------------------------------------------

    def _verify_locked(self) -> bool:
        """Digest base (+ flushed twins) against a fresh gen-bounded
        rebuild. Draining under the store's write lock pins an index at
        which the subscription is provably complete, so the compare is
        exact — no retries, no tolerance. Mismatch records a violation
        and forces a resync (repair, never poison the build path)."""
        import jax

        ep = self._epoch
        if ep is None or ep.stale:
            return True
        store = self.store
        with store._write_lock:
            evs = self.sub.next_events(timeout=0)
            truncated = self.sub.truncated
            self.sub.truncated = False
            snap = store.snapshot()
        try:
            self._alloc_uncounted += sum(1 for e in evs
                                         if e.topic == "Allocation")
            if truncated:
                self._resync_locked(ep.layout, ep.node_index, ep.n_pad)
                if self._epoch is not None:
                    self._epoch.static_ref = ep.static_ref
                return True
            for e in evs:
                if e.index <= ep.position:
                    continue    # resync floor; never advanced per event
                self._fold(ep, e)
            gen = snap.index
            n = len(ep.layout)
            truth = np.zeros((ep.n_pad, RESOURCE_DIMS))
            usage = store._node_usage
            for nid, i in ep.node_index.items():
                vec = usage.get(nid, gen)
                if vec is not None:
                    truth[i] = vec
        finally:
            snap.close()
        self._parity_checks += 1
        ok = np.array_equal(ep.base, truth)
        if ok:
            for key, tw in list(ep.twins.items()):
                if tw.cursor < len(ep.devlog):
                    continue        # unflushed: checked after next flush
                got = np.asarray(jax.device_get(tw.arr))
                if not np.array_equal(got, ep.base.astype(np.float32)):
                    ok = False
                    self.tracker.record(Violation(
                        "state-divergence",
                        f"device twin diverged from host base "
                        f"(mesh={'yes' if key is not None else 'no'}, "
                        f"n={n}, index {gen})"))
                    break
        else:
            bad = [ep.layout[i] for i in
                   np.nonzero(~np.all(ep.base[:n] == truth[:n],
                                      axis=1))[0][:8]]
            self.tracker.record(Violation(
                "state-divergence",
                f"incremental base diverged from snapshot rebuild at "
                f"index {gen} ({self._resyncs} resync(s), "
                f"{self._deltas_applied} delta(s)): node(s) {bad}"))
        if not ok:
            self._epoch = None      # force resync: repair, don't wedge
        self._gauges()
        return ok

    def _gauges(self) -> None:
        # base_for calls this on EVERY fast hit: skip the (process-
        # global-locked) registry writes unless a counter moved, or 24
        # racing workers convoy on the registry lock inside the
        # tensor_build span
        vals = (self._resyncs, self._deltas_applied, self._parity_checks)
        if vals == self._gauge_pub:
            return
        self._gauge_pub = vals
        from ..core.metrics import REGISTRY
        REGISTRY.set_gauge("nomad.state.resyncs", float(self._resyncs))
        REGISTRY.set_gauge("nomad.state.deltas_applied",
                           float(self._deltas_applied))
        REGISTRY.set_gauge("nomad.state.parity_checks",
                           float(self._parity_checks))


def _pad_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class StateTracker:
    """Registry of incremental feeds + their parity violations. Mirrors
    the shadow tracker's surface so conftest/chaos treat both prongs
    uniformly; unlike the shadow, feeds attach in PRODUCTION (the kill
    switch gates use, not attach) — san_active only arms the periodic
    parity digests."""

    def __init__(self):
        self.san_active = False
        self._ilock = _REAL_LOCK()
        self.feeds: List[IncrementalFeed] = []
        self.violations: List[Violation] = []

    def install(self) -> None:
        self.san_active = True

    def uninstall(self) -> None:
        self.san_active = False

    def attach(self, store, broker) -> Optional[IncrementalFeed]:
        # unwrap write facades (raft's RaftStore): the feed must key on
        # the snapshot-owning StateStore, because consumers find it via
        # snapshot._store identity (feed_for)
        store = getattr(store, "_store", store)
        existing = getattr(store, "_incremental_feed", None)
        if existing is not None:
            return existing
        feed = IncrementalFeed(store, broker, self)
        store._incremental_feed = feed
        with self._ilock:
            self.feeds.append(feed)
        return feed

    def record(self, v: Violation) -> None:
        with self._ilock:
            self.violations.append(v)

    def verify_all(self) -> List[str]:
        """Force a parity digest on every feed; rendered violations
        after. The chaos invariant sweep's view of the device state."""
        with self._ilock:
            feeds = list(self.feeds)
        for feed in feeds:
            feed.force_verify()
        return [v.render() for v in self.violations]

    def check(self) -> None:
        if self.violations:
            raise AssertionError(
                "nomadstate violations:\n"
                + "\n".join(v.render() for v in self.violations))

    def stats(self) -> Dict[str, int]:
        with self._ilock:
            feeds = list(self.feeds)
        out = {"feeds": len(feeds), "builds": 0, "fast_hits": 0,
               "resyncs": 0, "deltas_applied": 0, "parity_checks": 0}
        for f in feeds:
            for k, v in f.stats().items():
                out[k] += v
        return out

    def report(self) -> str:
        s = self.stats()
        lines = [
            f"nomadstate: {len(self.violations)} violation(s); "
            f"feeds={s['feeds']} builds={s['builds']} "
            f"fast_hits={s['fast_hits']} resyncs={s['resyncs']} "
            f"deltas={s['deltas_applied']} parity={s['parity_checks']}"]
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


# -- module-level surface (server wiring + conftest + chaos) --------------

GLOBAL = StateTracker()


def install() -> None:
    GLOBAL.install()


def uninstall() -> None:
    GLOBAL.uninstall()


def maybe_attach(store, broker) -> Optional[IncrementalFeed]:
    """Server-side hook next to shadow.maybe_attach: one feed per
    (store, broker) pair, idempotent."""
    return GLOBAL.attach(store, broker)


def feed_for(store) -> Optional[IncrementalFeed]:
    return getattr(store, "_incremental_feed", None) if store is not None \
        else None


def device_used_fn(store, static):
    """A (mesh) -> device array | None closure for the bulk solver's
    resync, or None when no feed serves this store."""
    feed = feed_for(store)
    if feed is None or static is None or not incr_enabled():
        return None

    def fn(mesh=None):
        return feed.device_used(static, mesh)

    return fn


def violations() -> List[Violation]:
    return list(GLOBAL.violations)


def check() -> None:
    GLOBAL.check()
