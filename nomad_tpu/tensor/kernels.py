"""JAX placement kernels.

Reproduces the reference scoring pipeline (scheduler/rank.go:205-835,
nomad/structs/funcs.go:236-278, scheduler/spread.go) as dense vector math
over all nodes at once, and the greedy placement loop
(generic_sched.go:511 computePlacements) as a `lax.scan` whose carry is
the cluster usage state — so each placement sees every earlier one, the
same commit-visibility contract the host path gets via
ctx.proposed_allocs.

Where the host path subsamples candidates (limit = max(2, ceil(log2 N)),
reference stack.go:82-95), the kernel scores *all* nodes and argmaxes —
strictly better placements at the same asymptotic cost, because the MXU
eats the (K x N) score matrix whole.

Shapes (padded to powers of two by the caller for compile-cache reuse):
  N nodes, D=3 resource dims, K placements, S spread attrs, V interned
  attribute-value vocabulary.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG = -1.0e30  # "infeasible" score sentinel
# additive tie-break jitter for the bulk engine's sort key (see
# solve_bulk_multi). Sized between the two constraints: far below any
# meaningful score gap (normalized scores live in [0, ~1.5] and the
# bench's score-parity margin is ~1e-3), far ABOVE the f32 ulp at the
# top of that range (np.spacing(1.0f) = 1.19e-7 — a jitter at or below
# the ulp would be rounded away exactly where BestFit ties concentrate,
# collapsing racing workers onto the same nodes again)
TIE_JITTER = 3.0e-5
BINPACK_MAX_FIT_SCORE = 18.0  # reference scheduler/rank.go:18


def _free_fractions_xp(xp, available, used):
    """Free fraction per (node, dim) after `used` is placed
    (reference funcs.go:213 computeFreePercentage).

    x/0 capacity -> -inf free (its 10^free term vanishes); 0/0 -> 0.0.

    `xp` is the array namespace (jnp on the device path, numpy on the
    host oracle) — the ONE copy of the formula, so the host fallback,
    the greedy kernel, and the batch solver cannot drift apart.
    """
    safe = xp.where(available > 0, available, 1.0)
    ratio = xp.where(
        available > 0,
        used / safe,
        xp.where(used > 0, xp.inf, 0.0),
    )
    return 1.0 - ratio


def _fit_scores_xp(xp, available, used, spread_alg):
    free = _free_fractions_xp(xp, available, used)
    total = 10.0 ** free[..., 0] + 10.0 ** free[..., 1]
    binpack = xp.clip(20.0 - total, 0.0, BINPACK_MAX_FIT_SCORE)
    spread = xp.clip(total - 2.0, 0.0, BINPACK_MAX_FIT_SCORE)
    return xp.where(spread_alg, spread, binpack) / BINPACK_MAX_FIT_SCORE


def _free_fractions(available: jnp.ndarray, used: jnp.ndarray) -> jnp.ndarray:
    return _free_fractions_xp(jnp, available, used)


def fit_scores(available: jnp.ndarray, used: jnp.ndarray,
               spread_alg: jnp.ndarray) -> jnp.ndarray:
    """Normalized fit score per node in [0, 1].

    binpack (BestFit-v3): clip(20 - (10^freeCpu + 10^freeMem), 0, 18)/18
    spread  (WorstFit):   clip((10^freeCpu + 10^freeMem) - 2, 0, 18)/18
    (reference funcs.go:236 ScoreFitBinPack / :263 ScoreFitSpread)
    """
    return _fit_scores_xp(jnp, available, used, spread_alg)


def fit_scores_np(available, used, spread_alg=False):
    """Numpy twin of `fit_scores` — same `_fit_scores_xp` core, so the
    host oracle (`tensor/placer._binpack_fitness_np`), the tests, and
    the bench score the exact formula the kernels run on device."""
    import numpy as np
    return _fit_scores_xp(np, np.asarray(available, dtype=np.float64),
                          np.asarray(used, dtype=np.float64), spread_alg)


def _pairwise_sum_xp(xp, v):
    """Fixed-tree pairwise sum over the LEADING axis. A plain ``.sum()``
    leaves the float add order to the backend's reduction strategy,
    which varies with the surrounding fusion context — the same
    contributions summed inside two different compiled graphs
    (single-device vs mesh-sharded) can disagree in the last ulp, and
    that is enough to flip a near-tied selection. Explicit halving adds
    pin the association order by shape alone, so every layout reduces
    identically bit-for-bit. 1-D input reduces to a scalar; (S, ...)
    input reduces axis 0 elementwise (the jnp.sum(x, axis=0) twin)."""
    n = int(v.shape[0])
    p = 1
    while p < n:
        p *= 2
    if p != n:
        v = xp.concatenate(
            [v, xp.zeros((p - n,) + tuple(v.shape[1:]), dtype=v.dtype)])
    while v.shape[0] > 1:
        v = v[0::2] + v[1::2]
    return v[0]


def score_nodes(
    *,
    available,        # (N, D) node capacity minus reserved; D = 4 base
                      #        dims + one column per device ask + one for
                      #        reserved cores when the group asks for them
    used,             # (N, D) current proposed usage
    ask,              # (D,)   task-group resource ask
    feasible,         # (N,)   bool: constraints+drivers+devices mask
    placed_tg,        # (N,)   proposed allocs of this job+tg per node
    placed_job,       # (N,)   proposed allocs of this job per node
    affinity_boost,   # (N,)   precomputed sum(weight)/sum|weight| per node
    dev_affinity,     # (N,)   device-affinity sub-score per node (0 = absent)
    penalty_idx,      # ()     node index to penalize (-1 = none)
    spread_val_id,    # (S, N) interned spread-attr value per node
    spread_val_ok,    # (S, N) bool: node has the attribute
    spread_counts,    # (S, V) combined existing+proposed counts per value
    spread_desired,   # (S, V) desired count per value (NaN = no target)
    spread_has_targets,  # (S,) bool: explicit targets vs even-spread
    spread_weight,    # (S,)  weight / sum|weights|
    dp_val_id,        # (P, N) interned distinct_property value per node
    dp_val_ok,        # (P, N) bool: node has the property
    dp_counts,        # (P, Vd) proposed alloc count per property value
    dp_limit,         # (P,)   max allocs per value (propertyset rtarget)
    lowest_boost,     # ()    running minimum explicit boost (spread.go)
    tg_count,         # ()    task group desired count
    dh_job,           # ()    bool: job-level distinct_hosts
    dh_tg,            # ()    bool: group-level distinct_hosts
    spread_alg,       # ()    bool: WorstFit instead of BestFit
):
    """Score every node for one placement. Returns (score, fitness) each
    (N,); infeasible nodes score NEG.

    Mirrors the host oracle NodeScorer.rank (scheduler/rank.py): the final
    score is the *mean of the sub-scores that apply* (reference
    rank.go:800 ScoreNormalizationIterator) — each sub-score carries a
    presence flag and the divisor is the number of present sub-scores.
    Fit scoring only reads the first two columns (cpu, mem — reference
    funcs.go:213), so the appended device/core columns participate in
    feasibility without perturbing the score.
    """
    n = available.shape[0]
    new_used = used + ask[None, :]

    ok = feasible & jnp.all(new_used <= available, axis=1)
    ok &= jnp.where(dh_job, placed_job == 0, True)
    ok &= jnp.where(dh_tg, placed_tg == 0, True)

    # distinct_property cap (reference scheduler/propertyset.go via
    # feasible.go:649 DistinctPropertyIterator): a node is infeasible if
    # it lacks the property or its value's proposed count is at the limit
    if dp_val_id.shape[0]:
        dp_at = jnp.take_along_axis(dp_counts, dp_val_id, axis=1)  # (P, N)
        dp_ok = dp_val_ok & (dp_at < dp_limit[:, None])
        ok &= jnp.all(dp_ok, axis=0)

    fitness = fit_scores(available, new_used, spread_alg)

    # job anti-affinity (reference rank.go:596)
    anti_present = placed_tg > 0
    anti = -(placed_tg.astype(fitness.dtype) + 1.0) / jnp.maximum(tg_count, 1.0)

    # node rescheduling penalty (reference rank.go:666)
    resched_present = jnp.arange(n) == penalty_idx

    # node affinity (reference rank.go:710); boost precomputed host-side
    aff_present = affinity_boost != 0.0

    # device affinity (host oracle's separate "device-affinity" sub-score;
    # reference rank.go folds the deviceAllocator offer score in)
    dev_present = dev_affinity != 0.0

    # spread (reference spread.go:128 + propertyset.go)
    counts_at = jnp.take_along_axis(spread_counts, spread_val_id, axis=1)  # (S, N)
    used_cnt = counts_at.astype(fitness.dtype) + 1.0  # incl. this placement
    desired = jnp.take_along_axis(spread_desired, spread_val_id, axis=1)   # (S, N)

    explicit = jnp.where(
        jnp.isnan(desired),
        -1.0,
        jnp.where(
            desired == 0.0,
            lowest_boost,
            (desired - used_cnt) / jnp.where(desired == 0.0, 1.0, desired)
            * spread_weight[:, None],
        ),
    )
    explicit = jnp.where(spread_val_ok, explicit, -1.0)

    # even-spread boost (reference spread.go evenSpreadScoreBoost): uses
    # combined counts *without* the current placement
    present_v = spread_counts > 0                                   # (S, V)
    any_present = jnp.any(present_v, axis=1)                        # (S,)
    minc = jnp.min(jnp.where(present_v, spread_counts, jnp.iinfo(jnp.int32).max),
                   axis=1).astype(fitness.dtype)                    # (S,)
    maxc = jnp.max(jnp.where(present_v, spread_counts, 0),
                   axis=1).astype(fitness.dtype)                    # (S,)
    cur = counts_at.astype(fitness.dtype)                           # (S, N)
    minc_b = minc[:, None]
    maxc_b = maxc[:, None]
    even = jnp.where(
        cur != minc_b,
        jnp.where(minc_b == 0.0, -1.0,
                  (minc_b - cur) / jnp.where(minc_b == 0.0, 1.0, minc_b)),
        jnp.where(minc_b == maxc_b, -1.0,
                  jnp.where(minc_b == 0.0, 1.0,
                            (maxc_b - minc_b) / jnp.where(minc_b == 0.0, 1.0, minc_b))),
    )
    # empty property set -> boost 0 (spread.go evenSpreadScoreBoost early
    # return), but the missing-attribute -1.0 penalty applies regardless
    # (SpreadScorer.score checks `ok` before consulting the property set)
    even = jnp.where(any_present[:, None], even, 0.0)
    even = jnp.where(spread_val_ok, even, -1.0)

    boost = jnp.where(spread_has_targets[:, None], explicit, even)  # (S, N)
    # fixed-tree reduction: spread_total feeds the != 0 presence test,
    # so its float add order must not vary with the fusion context
    spread_total = _pairwise_sum_xp(jnp, boost)                     # (N,)
    spread_present = spread_total != 0.0

    divisor = (
        1.0
        + anti_present.astype(fitness.dtype)
        + resched_present.astype(fitness.dtype)
        + aff_present.astype(fitness.dtype)
        + dev_present.astype(fitness.dtype)
        + spread_present.astype(fitness.dtype)
    )
    total = (
        fitness
        + jnp.where(anti_present, anti, 0.0)
        + jnp.where(resched_present, -1.0, 0.0)
        + jnp.where(aff_present, affinity_boost, 0.0)
        + jnp.where(dev_present, dev_affinity, 0.0)
        + jnp.where(spread_present, spread_total, 0.0)
    )
    final = total / divisor
    return jnp.where(ok, final, NEG), fitness, boost


def _permute_node_axis(tie_perm, available, used0, placed_tg0, placed_job0,
                       feasible, affinity_boost, dev_affinity,
                       spread_val_id, spread_val_ok, dp_val_id, dp_val_ok):
    """Gather every per-node array into tie-break-permuted space — the
    single definition shared by the per-placement scan and the bulk
    solver, so a new per-node input can't be permuted in one and
    forgotten in the other."""
    return (available[tie_perm], used0[tie_perm], placed_tg0[tie_perm],
            placed_job0[tie_perm], feasible[tie_perm],
            affinity_boost[tie_perm], dev_affinity[tie_perm],
            spread_val_id[:, tie_perm], spread_val_ok[:, tie_perm],
            dp_val_id[:, tie_perm] if dp_val_id.shape[0] else dp_val_id,
            dp_val_ok[:, tie_perm] if dp_val_ok.shape[0] else dp_val_ok)


@partial(jax.jit, donate_argnums=())
def solve_task_group(
    available,         # (N, D)
    used0,             # (N, D)
    placed_tg0,        # (N,)  int32
    placed_job0,       # (N,)  int32
    ask,               # (D,)
    feasible,          # (N,)  bool
    affinity_boost,    # (N,)
    dev_affinity,      # (N,)
    penalty_idx,       # (K,)  int32, -1 = none
    active,            # (K,)  bool (False = padding step)
    spread_val_id,     # (S, N) int32
    spread_val_ok,     # (S, N) bool
    spread_counts0,    # (S, V) int32
    spread_desired,    # (S, V)
    spread_has_targets,  # (S,) bool
    spread_weight,     # (S,)
    dp_val_id,         # (P, N) int32
    dp_val_ok,         # (P, N) bool
    dp_counts0,        # (P, Vd) int32
    dp_limit,          # (P,)
    lowest_boost0,     # ()
    tg_count,          # ()
    dh_job,            # () bool
    dh_tg,             # () bool
    spread_alg,        # () bool
    tie_perm=None,     # (N,) int32 permutation: tie-break priority order
):
    """Place K allocations of one task group. Returns per-step
    (choice, found, score): the chosen node index, whether any node fit,
    and the winning normalized score.

    The scan carry is the proposed cluster state — usage, per-node
    placement counts, spread value counts, distinct_property value
    counts — exactly the state the host path threads through
    ctx.proposed_allocs + SpreadScorer + propertyset between placements
    (generic_sched.go:511-600 commit loop).

    tie_perm replaces the host path's per-eval node shuffle (reference
    scheduler/util.go:167 shuffleNodes): the whole solve runs in
    PERMUTED node space (one up-front gather of every per-node array, so
    the scan body stays a plain argmax) and choices map back through the
    permutation at the end. Equal-scoring winners follow the
    permutation's priority order — racing workers diverge on ties
    without reordering the (cached, canonical) per-node arrays
    host-side.
    """
    s = spread_val_id.shape[0]
    p = dp_val_id.shape[0]
    n = available.shape[0]
    if tie_perm is not None:
        (available, used0, placed_tg0, placed_job0, feasible,
         affinity_boost, dev_affinity, spread_val_id, spread_val_ok,
         dp_val_id, dp_val_ok) = _permute_node_axis(
            tie_perm, available, used0, placed_tg0, placed_job0, feasible,
            affinity_boost, dev_affinity, spread_val_id, spread_val_ok,
            dp_val_id, dp_val_ok)
        inv = jnp.zeros(n, jnp.int32).at[tie_perm].set(
            jnp.arange(n, dtype=jnp.int32))
        penalty_idx = jnp.where(penalty_idx >= 0, inv[penalty_idx], -1)

    def step(carry, xs):
        used, ptg, pjob, scnt, dpcnt, lowest = carry
        pen_idx, is_active = xs

        score, fitness, boost = score_nodes(
            available=available, used=used, ask=ask, feasible=feasible,
            placed_tg=ptg, placed_job=pjob, affinity_boost=affinity_boost,
            dev_affinity=dev_affinity, penalty_idx=pen_idx,
            spread_val_id=spread_val_id, spread_val_ok=spread_val_ok,
            spread_counts=scnt, spread_desired=spread_desired,
            spread_has_targets=spread_has_targets, spread_weight=spread_weight,
            dp_val_id=dp_val_id, dp_val_ok=dp_val_ok, dp_counts=dpcnt,
            dp_limit=dp_limit,
            lowest_boost=lowest, tg_count=tg_count,
            dh_job=dh_job, dh_tg=dh_tg, spread_alg=spread_alg,
        )
        choice = jnp.argmax(score)
        found = is_active & (score[choice] > NEG)

        onehot = (jnp.arange(n) == choice) & found
        used = used + ask[None, :] * onehot[:, None]
        ptg = ptg + onehot.astype(ptg.dtype)
        pjob = pjob + onehot.astype(pjob.dtype)

        sel_ok = spread_val_ok[:, choice] & found                  # (S,)
        sel_val = spread_val_id[:, choice]                          # (S,)
        scnt = scnt.at[jnp.arange(s), sel_val].add(sel_ok.astype(scnt.dtype))

        if p:
            dsel_ok = dp_val_ok[:, choice] & found                 # (P,)
            dsel_val = dp_val_id[:, choice]                        # (P,)
            dpcnt = dpcnt.at[jnp.arange(p), dsel_val].add(
                dsel_ok.astype(dpcnt.dtype))

        # SpreadIterator tracks the lowest explicit boost it has handed
        # out (spread.go lowestBoost); we update it with the chosen
        # node's explicit boosts
        chosen_boost = jnp.where(spread_has_targets & sel_ok,
                                 boost[:, choice], jnp.inf)
        lowest = jnp.minimum(lowest, jnp.min(chosen_boost, initial=jnp.inf))

        return (used, ptg, pjob, scnt, dpcnt, lowest), (choice, found, score[choice])

    init = (used0, placed_tg0, placed_job0, spread_counts0, dp_counts0,
            lowest_boost0)
    _, (choices, founds, scores) = jax.lax.scan(
        init=init, f=step, xs=(penalty_idx, active))
    if tie_perm is not None:
        choices = tie_perm[choices]
    return choices, founds, scores


# ---------------------------------------------------------------------------
# fused transfer layout
# ---------------------------------------------------------------------------
#
# Device round trips, not FLOPs, bound small solves (the real chip sits
# behind a tunnel; each host<->device hop costs ~10-150 ms). The fused
# entry point packs the 20 logical arguments into 6 arrays and returns
# one packed output so a whole task-group solve costs one upload batch
# and one readback.
#
# node_mat (N, 2D+6): avail[D] | used[D] | placed_tg | placed_job | feasible
#                     | affinity | dev_affinity | tie_perm
# step_mat (K, 2):  penalty_idx | active
# spread_node (2S, N): val_id rows then val_ok rows
# spread_tab (2S, V):  counts rows then desired rows
# spread_meta (S, 2):  has_targets | weight
# dp_node (2P, N): val_id rows then val_ok rows
# dp_tab (P, Vd+1): counts columns | limit column
# scalars (5+D,): lowest_boost | tg_count | dh_job | dh_tg | spread_alg | ask[D]


def pack_solve_args(available, used0, placed_tg0, placed_job0, ask, feasible,
                    affinity_boost, penalty_idx, active, spread_val_id,
                    spread_val_ok, spread_counts0, spread_desired,
                    spread_has_targets, spread_weight, lowest_boost0,
                    tg_count, dh_job, dh_tg, spread_alg,
                    dev_affinity=None, dp_val_id=None, dp_val_ok=None,
                    dp_counts0=None, dp_limit=None, tie_perm=None):
    """Host-side packing (numpy) for solve_task_group_fused."""
    import numpy as np

    f = np.float32
    n = np.asarray(available).shape[0]
    if dev_affinity is None:
        dev_affinity = np.zeros(n, f)
    if tie_perm is None:
        tie_perm = np.arange(n)
    node_mat = np.concatenate([
        np.asarray(available, f), np.asarray(used0, f),
        np.asarray(placed_tg0, f)[:, None], np.asarray(placed_job0, f)[:, None],
        np.asarray(feasible, f)[:, None], np.asarray(affinity_boost, f)[:, None],
        np.asarray(dev_affinity, f)[:, None], np.asarray(tie_perm, f)[:, None],
    ], axis=1)
    step_mat = np.stack([np.asarray(penalty_idx, f),
                         np.asarray(active, f)], axis=1)
    spread_node = np.concatenate([np.asarray(spread_val_id, f),
                                  np.asarray(spread_val_ok, f)], axis=0)
    spread_tab = np.concatenate([np.asarray(spread_counts0, f),
                                 np.asarray(spread_desired, f)], axis=0)
    spread_meta = np.stack([np.asarray(spread_has_targets, f),
                            np.asarray(spread_weight, f)], axis=1) \
        if len(spread_weight) else np.zeros((0, 2), f)
    if dp_val_id is None or not len(dp_val_id):
        dp_node = np.zeros((0, n), f)
        dp_tab = np.zeros((0, 2), f)
    else:
        dp_node = np.concatenate([np.asarray(dp_val_id, f),
                                  np.asarray(dp_val_ok, f)], axis=0)
        dp_tab = np.concatenate([np.asarray(dp_counts0, f),
                                 np.asarray(dp_limit, f)[:, None]], axis=1)
    scalars = np.concatenate([
        np.array([lowest_boost0, tg_count, dh_job, dh_tg, spread_alg], f),
        np.asarray(ask, f)])
    return (node_mat, step_mat, spread_node, spread_tab, spread_meta,
            dp_node, dp_tab, scalars)


@jax.jit
def solve_task_group_fused(node_mat, step_mat, spread_node, spread_tab,
                           spread_meta, dp_node, dp_tab, scalars):
    """Transfer-fused solve: unpack on device, run the same scan, return
    one (3, K) array of [choice, found, score] rows."""
    s = spread_meta.shape[0]
    p = dp_node.shape[0] // 2
    d = (node_mat.shape[1] - 6) // 2
    choices, founds, scores = solve_task_group(
        node_mat[:, 0:d], node_mat[:, d:2 * d],
        node_mat[:, 2 * d].astype(jnp.int32),
        node_mat[:, 2 * d + 1].astype(jnp.int32),
        scalars[5:5 + d], node_mat[:, 2 * d + 2] > 0.5, node_mat[:, 2 * d + 3],
        node_mat[:, 2 * d + 4],
        step_mat[:, 0].astype(jnp.int32), step_mat[:, 1] > 0.5,
        spread_node[:s].astype(jnp.int32), spread_node[s:] > 0.5,
        spread_tab[:s].astype(jnp.int32), spread_tab[s:],
        spread_meta[:, 0] > 0.5, spread_meta[:, 1],
        dp_node[:p].astype(jnp.int32), dp_node[p:] > 0.5,
        dp_tab[:, :-1].astype(jnp.int32), dp_tab[:, -1],
        scalars[0], scalars[1], scalars[2] > 0.5, scalars[3] > 0.5,
        scalars[4] > 0.5,
        node_mat[:, 2 * d + 5].astype(jnp.int32),
    )
    return jnp.stack([choices.astype(scores.dtype),
                      founds.astype(scores.dtype), scores])


# ---------------------------------------------------------------------------
# bulk solve: K identical placements as counts, O(K/B) sequential steps
# ---------------------------------------------------------------------------
#
# The C2M engine. A fresh job's task group asks for K identical
# placements; the per-placement scan costs K sequential steps (the
# sequential chain is the latency floor at K=4096). This solver instead
# assigns a BATCH of B placements per step: score all nodes once
# (identical math to score_nodes), then give the best-scoring nodes
# their fill in score order — per-node capacity for binpack (the greedy
# winner keeps winning until full, so fill-to-capacity IS the greedy
# trajectory), one per node per step for spread (approximating the
# round-robin; parity is measured, not assumed). Counts, not choices,
# come back: one (N,) readback regardless of K. This is the
# "batched feasibility masking + scoring + assignment" shape BASELINE.md
# names as the north-star design.


def _bulk_scan(
    available,         # (N, D)
    used0,             # (N, D)
    ask,               # (D,)
    feasible,          # (N,) bool
    placed_tg0,        # (N,) int32
    placed_job0,       # (N,) int32
    affinity_boost,    # (N,)
    dev_affinity,      # (N,)
    spread_val_id,     # (S, N) int32
    spread_val_ok,     # (S, N) bool
    spread_counts0,    # (S, V) int32
    spread_desired,    # (S, V)
    spread_has_targets,  # (S,) bool
    spread_weight,     # (S,)
    k_total,           # () int32 placements wanted
    tg_count,          # ()
    dh_job,            # () bool
    dh_tg,             # () bool
    spread_alg,        # () bool
    tie_perm,          # (N,) int32
    *,
    batch: int,        # placements per step
    n_steps: int,      # static scan length >= ceil(k_total / batch)
):
    """-> (N,) int32 per-node placement counts in canonical order —
    ONE readback regardless of K. Runs in permuted node space like
    solve_task_group; counts map back at the end. (The trajectory's
    mean score is recomputed host-side by _bulk_trajectory_mean — the
    step-start scores here under-report a fill-to-capacity batch.)"""
    n = available.shape[0]
    s = spread_val_id.shape[0]
    dp_val_id = jnp.zeros((0, n), jnp.int32)
    dp_val_ok = jnp.zeros((0, n), bool)
    dp_counts = jnp.zeros((0, 1), jnp.int32)
    dp_limit = jnp.zeros(0)
    (available, used0, placed_tg0, placed_job0, feasible,
     affinity_boost, dev_affinity, spread_val_id, spread_val_ok,
     dp_val_id, dp_val_ok) = _permute_node_axis(
        tie_perm, available, used0, placed_tg0, placed_job0, feasible,
        affinity_boost, dev_affinity, spread_val_id, spread_val_ok,
        dp_val_id, dp_val_ok)

    # per-node max one placement under distinct_hosts; else fill for
    # binpack, one-per-step for spread (WorstFit drops a node's score
    # after each placement, so greedy round-robins)
    single = dh_job | dh_tg | spread_alg

    ask_pos = ask > 0

    def step(carry, _):
        used, ptg, pjob, scnt, taken, remaining = carry
        score, _, _ = score_nodes(
            available=available, used=used, ask=ask, feasible=feasible,
            placed_tg=ptg, placed_job=pjob, affinity_boost=affinity_boost,
            dev_affinity=dev_affinity, penalty_idx=jnp.int32(-1),
            spread_val_id=spread_val_id, spread_val_ok=spread_val_ok,
            spread_counts=scnt, spread_desired=spread_desired,
            spread_has_targets=spread_has_targets, spread_weight=spread_weight,
            dp_val_id=dp_val_id, dp_val_ok=dp_val_ok, dp_counts=dp_counts,
            dp_limit=dp_limit,
            lowest_boost=-1.0, tg_count=tg_count,
            dh_job=dh_job, dh_tg=dh_tg, spread_alg=spread_alg,
        )
        budget = jnp.minimum(remaining, batch)
        # how many MORE fit on each node; a zero ask in every dimension
        # means infinite per-node capacity, so clamp to the step budget
        # BEFORE the int32 cast (inf -> INT32_MAX would overflow the
        # cumsum below)
        free = available - used
        per_dim = jnp.where(ask_pos[None, :], jnp.floor(free / jnp.where(
            ask_pos, ask, 1.0)[None, :]), jnp.inf)
        cap = jnp.min(per_dim, axis=1)
        cap = jnp.clip(cap, 0, None)
        cap = jnp.where(score > NEG, cap, 0.0)
        cap = jnp.where(single, jnp.minimum(cap, 1.0), cap)
        cap = jnp.minimum(cap, budget.astype(cap.dtype)).astype(jnp.int32)
        order = jnp.argsort(-score)               # stable: ties by index
        cap_sorted = cap[order]
        cum = jnp.cumsum(cap_sorted)
        take_sorted = jnp.clip(budget - (cum - cap_sorted), 0, cap_sorted)
        take = jnp.zeros(n, jnp.int32).at[order].set(take_sorted)

        used = used + ask[None, :] * take[:, None].astype(used.dtype)
        ptg = ptg + take
        pjob = pjob + take
        if s:
            scnt = scnt.at[jnp.arange(s)[:, None], spread_val_id].add(
                jnp.where(spread_val_ok, take[None, :], 0))
        placed_now = jnp.sum(take).astype(jnp.int32)
        return (used, ptg, pjob, scnt, taken + take,
                remaining - placed_now), None

    init = (used0, placed_tg0, placed_job0, spread_counts0,
            jnp.zeros(n, jnp.int32), jnp.int32(k_total))
    (used, ptg, pjob, scnt, taken, remaining), _ = jax.lax.scan(
        init=init, f=step, xs=None, length=n_steps)
    return jnp.zeros(n, jnp.int32).at[tie_perm].set(taken)


solve_bulk = partial(jax.jit, static_argnames=("batch", "n_steps"))(_bulk_scan)


@partial(jax.jit, static_argnames=("batch", "n_steps"))
def solve_bulk_fused(
    available,   # (N, D) — device-RESIDENT per node-set version
    feasible,    # (N,) bool — resident per task-group mask signature
    aff,         # (N,) — resident per affinity signature
    dyn,         # (N, D+2) float32: used | placed_tg | placed_job (per eval)
    ask,         # (D,)
    k_total,     # () int32
    tg_count,    # () float
    seed,        # () uint32: tie-break permutation PRNG seed
    *,
    batch: int,
    n_steps: int,
):
    """Transfer-minimal bulk solve: the big static arrays live on the
    device across evals (the tunnel moves ~100ms per synchronous hop —
    see the fused-transfer note above); each eval ships one (N, D+2)
    f32 matrix + a handful of scalars, and the tie-break permutation is
    generated ON DEVICE from the seed. No spread/dh/dp tables by bulk
    eligibility (placer._bulk_eligible)."""
    n, d = available.shape
    tie_perm = jax.random.permutation(
        jax.random.PRNGKey(seed), n).astype(jnp.int32)
    f = available.dtype
    return _bulk_scan(
        available, dyn[:, :d].astype(f), ask.astype(f), feasible,
        dyn[:, d].astype(jnp.int32), dyn[:, d + 1].astype(jnp.int32),
        aff.astype(f), jnp.zeros(n, f),
        jnp.zeros((0, n), jnp.int32), jnp.zeros((0, n), bool),
        jnp.zeros((0, 1), jnp.int32), jnp.zeros((0, 1), f),
        jnp.zeros(0, bool), jnp.zeros(0, f),
        k_total, tg_count, False, False, False, tie_perm,
        batch=batch, n_steps=n_steps)


@partial(jax.jit, static_argnames=())
def score_nodes_once(
    available, used, ask, feasible, placed_tg, placed_job, affinity_boost,
    penalty_idx, spread_val_id, spread_val_ok, spread_counts, spread_desired,
    spread_has_targets, spread_weight, lowest_boost, tg_count, dh_job, dh_tg,
    spread_alg, dev_affinity=None, dp_val_id=None, dp_val_ok=None,
    dp_counts=None, dp_limit=None,
):
    """Single-placement score vector — the differential-test surface
    pinned against the host oracle scheduler.rank.score_nodes."""
    n = available.shape[0]
    if dev_affinity is None:
        dev_affinity = jnp.zeros(n)
    if dp_val_id is None:
        dp_val_id = jnp.zeros((0, n), jnp.int32)
        dp_val_ok = jnp.zeros((0, n), bool)
        dp_counts = jnp.zeros((0, 1), jnp.int32)
        dp_limit = jnp.zeros(0)
    score, _, _ = score_nodes(
        available=available, used=used, ask=ask, feasible=feasible,
        placed_tg=placed_tg, placed_job=placed_job,
        affinity_boost=affinity_boost, dev_affinity=dev_affinity,
        penalty_idx=penalty_idx,
        spread_val_id=spread_val_id, spread_val_ok=spread_val_ok,
        spread_counts=spread_counts, spread_desired=spread_desired,
        spread_has_targets=spread_has_targets, spread_weight=spread_weight,
        dp_val_id=dp_val_id, dp_val_ok=dp_val_ok, dp_counts=dp_counts,
        dp_limit=dp_limit,
        lowest_boost=lowest_boost, tg_count=tg_count,
        dh_job=dh_job, dh_tg=dh_tg, spread_alg=spread_alg,
    )
    return score


def _solve_bulk_multi_impl(
    used0,       # (N, D) f32 usage carry — device-RESIDENT, donated back
    available,   # (N, D) f32 resident capacity
    feas,        # (G, N) bool stacked per-eval feasibility masks
    aff,         # (G, N) f32 stacked per-eval affinity boosts
    ask,         # (G, D) f32 per-eval resource asks
    k,           # (G,) int32 placements wanted per eval
    tg_count,    # (G,) f32 (kept for signature parity; scores are
                 #          recomputed host-side for the trajectory mean)
    seeds,       # (G,) uint32 per-eval tie-break seeds
    cidx,        # (C,) int32 usage-correction node rows (0 = no-op slot)
    cdelta,      # (C, D) f32 usage-correction deltas added to used0
                 #        before solving (rejected-placement phantoms
                 #        arrive negative; see tensor/solver.py ledger)
    *,
    g: int,
):
    """Chained bulk solves for G independent fresh-placement evals in ONE
    launch -> ((N, D) new usage carry staying on device, (G, N) int16
    per-node counts — the only readback).

    The tunnel to the device charges ~100ms of fixed latency per
    synchronous hop (measured in-round), so per-eval launches cap the
    whole pipeline; here the usage state never leaves the device between
    launches and the round trip amortizes over G evals. Eval i places
    k[i] allocations of ask[i] by BestFit fill-to-capacity against the
    usage state left by eval i-1, with tie-breaks from a per-eval
    on-device permutation of seeds[i] (same PRNG as solve_bulk_fused).

    ONE fill pass per eval, not a scan of score-refresh steps: a node's
    BestFit score depends only on its own usage, so filling the best
    node to capacity never re-orders the remaining nodes — the one-pass
    sorted fill IS the re-scored greedy trajectory (the refresh steps of
    _bulk_scan only repeat the score + full-sort work, ~12ms of device
    time per step at 10K nodes). The in-eval anti-affinity term is
    dropped for the same reason the trajectory tolerates it in
    _bulk_scan: under fill-to-capacity every chosen node saturates its
    capacity regardless of score magnitude, so the anti term can only
    affect reported scores (recomputed host-side), not choices, except
    through order among non-equal nodes — bounded by the same score
    parity the bulk path is benched against. No statics besides G, so
    the jit cache holds exactly two graph variants (G=1, G=G_PAD)."""
    n, d = available.shape
    f = available.dtype
    # fold queued usage corrections into the carry (scatter-add; the
    # clamp guards against a correction racing a concurrent resync)
    used0 = jnp.maximum(used0.at[cidx].add(cdelta), 0.0)
    # Tie-breaks: a per-(eval, node) additive score jitter << any
    # meaningful score gap replaces the old permutation+stable-argsort
    # scheme. Same decorrelation of racing workers' choices among
    # equal-scoring nodes, but the sort key becomes a plain float —
    # which is what lets the SHARDED twin of this kernel
    # (tensor/sharding.make_solve_bulk_multi_sharded) use per-shard
    # top-k + a small gathered merge instead of a replicated full sort.
    jits = jax.vmap(
        lambda s: jax.random.uniform(jax.random.PRNGKey(s), (n,),
                                     jnp.float32, 0.0, TIE_JITTER)
    )(seeds)                                                       # (G, N)

    def one_eval(used, gi):
        ask_g = ask[gi]
        ask_pos = ask_g > 0
        new_used = used + ask_g[None, :]
        ok = feas[gi] & jnp.all(new_used <= available, axis=1)
        fitness = fit_scores(available, new_used, False)
        aff_g = aff[gi]
        aff_present = aff_g != 0.0
        divisor = 1.0 + aff_present.astype(f)
        score = (fitness + jnp.where(aff_present, aff_g, 0.0)) / divisor
        score = jnp.where(ok, score, NEG)

        free = available - used
        per_dim = jnp.where(
            ask_pos[None, :],
            jnp.floor(free / jnp.where(ask_pos, ask_g, 1.0)[None, :]),
            jnp.inf)
        cap = jnp.clip(jnp.min(per_dim, axis=1), 0, None)
        cap = jnp.where(score > NEG, cap, 0.0)
        budget = k[gi]
        cap = jnp.minimum(cap, budget.astype(cap.dtype)).astype(jnp.int32)
        key = score + jits[gi]
        order = jnp.argsort(-key)            # residual ties: node index
        cap_sorted = cap[order]
        cum = jnp.cumsum(cap_sorted)
        take_sorted = jnp.clip(budget - (cum - cap_sorted), 0, cap_sorted)
        take = jnp.zeros(n, jnp.int32).at[order].set(take_sorted)
        used = used + ask_g[None, :] * take[:, None].astype(used.dtype)
        return used, take.astype(jnp.int16)

    used, counts = jax.lax.scan(one_eval, used0, jnp.arange(g))
    return used, counts


# public jitted entry; the raw impl stays importable so the batch solver
# (tensor/batch_solver.solve_batch) can inline the exact greedy chain as
# its baseline arm inside ONE launch instead of a second round trip
solve_bulk_multi = partial(jax.jit, static_argnames=("g",),
                           donate_argnums=(0,))(_solve_bulk_multi_impl)


@jax.jit
def preempt_pick(
    available,   # (N, D) capacity
    used0,       # (N, D) proposed usage
    evictable0,  # (N, D) sum of preemptible lower-priority alloc usage
    ask,         # (D,)
    feasible,    # (N,) bool constraint/driver mask
    net_prio,    # (N,) approximate netPriority of the node's preemptible
                 #      set: max + sum/max (reference rank.go netPriority
                 #      over the victim set; the per-node aggregate is an
                 #      upper bound used only to ORDER candidate nodes —
                 #      the host recomputes the exact score for the
                 #      chosen node's actual victims)
    active,      # (K,) bool request slots
):
    """Batched preemption node choice for K requests -> (K,) int32 node
    index per request (-1 = no preemptible node). Mirrors the host
    fallback's node ordering: fit score after eviction + the logistic
    preemption penalty (rank.go:894 preemptionScore), averaged like
    ScoreNormalizationIterator. The scan carries usage and remaining
    evictable capacity so sibling requests don't pile onto one node;
    exact victim selection stays host-side per chosen node
    (scheduler/preemption.py)."""
    f = available.dtype
    rate, origin = 0.0048, 2048.0
    pscore_node = 1.0 / (1.0 + jnp.exp(rate * (net_prio - origin)))

    def step(carry, i):
        used, evictable = carry
        new_used = used + ask[None, :]
        deficit = jnp.maximum(new_used - available, 0.0)
        can = feasible & jnp.all(deficit <= evictable, axis=1)
        needs_evict = jnp.any(deficit > 0.0, axis=1)
        fitness = fit_scores(available, jnp.minimum(new_used, available), False)
        divisor = 1.0 + needs_evict.astype(f)
        score = (fitness + jnp.where(needs_evict, pscore_node, 0.0)) / divisor
        score = jnp.where(can, score, NEG)
        best = jnp.argmax(score)
        found = (score[best] > NEG) & active[i]

        def apply(c):
            used, evictable = c
            used = used.at[best].set(
                jnp.minimum(used[best] + ask, available[best]))
            evictable = evictable.at[best].set(
                jnp.maximum(evictable[best] - deficit[best], 0.0))
            return used, evictable

        used, evictable = jax.lax.cond(found, apply, lambda c: c,
                                       (used, evictable))
        return (used, evictable), jnp.where(found, best, -1)

    _, picks = jax.lax.scan(step, (used0, evictable0),
                            jnp.arange(active.shape[0]))
    return picks.astype(jnp.int32)


@jax.jit
def preempt_solve(
    available,   # (N, D) capacity
    used0,       # (N, D) proposed usage
    ask,         # (D,)
    feasible,    # (N,) bool constraint/driver mask
    net_prio,    # (N,) approximate netPriority aggregate (see preempt_pick)
    active,      # (K,) bool request slots
    v_prio,      # (N, V) f32 victim priorities (column order: priority
                 #        asc, alloc id asc — scheduler.preemption.
                 #        victim_candidates' canonical order)
    v_vec,       # (N, V, D) f32 victim allocated resource vectors
    v_elig,      # (N, V) bool eligibility (delta-10 + usage-counting)
    v_flag,      # (N, V) bool port/device holders the dense columns
                 #        can't model — rows selecting one are flagged
                 #        for the exact host scanner
):
    """Whole preemption solve for K requests in ONE launch: node choice
    (same ordering as preempt_pick — fit after eviction + logistic
    preemption penalty) AND concrete victim selection.

    Victims are a priority-ascending PREFIX of the chosen node's
    still-unclaimed eligible column, taken until the deficit is covered
    in every resource dim (the kernel analog of preempt_for_task_group's
    ascending priority groups; within-group distance refinement and the
    filterSuperset drop stay host-side in the exact scanner, which is
    the fallback for flagged rows). The carry commits usage, remaining
    evictable capacity, and a per-victim `taken` mask so sibling
    requests in the same launch never double-claim a victim.

    Returns (picks (K,) i32 node or -1,
             victims (K, V) bool mask into the picked node's column,
             flagged (K,) bool — victim set includes an exact-resource
                     holder, route this row through the host scanner,
             scores (K,) f32 winning node score).
    """
    f = available.dtype
    rate, origin = 0.0048, 2048.0
    pscore_node = 1.0 / (1.0 + jnp.exp(rate * (net_prio - origin)))

    ev0 = jnp.sum(v_vec * v_elig[:, :, None].astype(f), axis=1)
    taken0 = jnp.zeros(v_prio.shape, dtype=bool)

    def step(carry, i):
        used, ev, taken = carry
        new_used = used + ask[None, :]
        deficit = jnp.maximum(new_used - available, 0.0)
        can = feasible & jnp.all(deficit <= ev, axis=1)
        needs_evict = jnp.any(deficit > 0.0, axis=1)
        fitness = fit_scores(available, jnp.minimum(new_used, available), False)
        divisor = 1.0 + needs_evict.astype(f)
        score = (fitness + jnp.where(needs_evict, pscore_node, 0.0)) / divisor
        score = jnp.where(can, score, NEG)
        best = jnp.argmax(score)
        found = (score[best] > NEG) & active[i]

        # priority-ascending prefix over the best node's unclaimed
        # eligible column: a victim is selected while ANY dim's deficit
        # is not yet covered by the victims before it (columns are
        # pre-sorted, so cumsum-before IS the prefix sum)
        row_elig = v_elig[best] & ~taken[best]
        vecs = v_vec[best] * row_elig[:, None].astype(f)
        cum_before = jnp.cumsum(vecs, axis=0) - vecs
        def_b = deficit[best]
        sel = (row_elig & needs_evict[best]
               & jnp.any((def_b[None, :] > 0.0)
                         & (cum_before < def_b[None, :]), axis=1))
        sel = sel & found
        evicted = jnp.sum(v_vec[best] * sel[:, None].astype(f), axis=0)
        flagged_i = jnp.any(sel & v_flag[best])

        def apply(c):
            used, ev, taken = c
            used = used.at[best].set(
                jnp.maximum(used[best] + ask - evicted, 0.0))
            ev = ev.at[best].set(jnp.maximum(ev[best] - evicted, 0.0))
            taken = taken.at[best].set(taken[best] | sel)
            return used, ev, taken

        used, ev, taken = jax.lax.cond(found, apply, lambda c: c,
                                       (used, ev, taken))
        return ((used, ev, taken),
                (jnp.where(found, best, -1), sel, flagged_i,
                 jnp.where(found, score[best], NEG)))

    _, (picks, victims, flagged, scores) = jax.lax.scan(
        step, (used0, ev0, taken0), jnp.arange(active.shape[0]))
    return (picks.astype(jnp.int32), victims, flagged, scores.astype(f))
