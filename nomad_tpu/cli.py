"""CLI (reference command/, 221 command files — the operational core).

  nomad-tpu agent -dev [--clients N] [--port P] [--algorithm A]
  nomad-tpu job run <spec.{json,hcl,nomad}>
  nomad-tpu job status [<job_id>]
  nomad-tpu job stop [-purge] <job_id>
  nomad-tpu node status [<node_id>]
  nomad-tpu node drain -enable|-disable <node_id>
  nomad-tpu node eligibility -enable|-disable <node_id>
  nomad-tpu alloc status <alloc_id>
  nomad-tpu eval status <eval_id>
  nomad-tpu operator scheduler get-config
  nomad-tpu operator scheduler set-config -scheduler-algorithm <alg>

Run via `python -m nomad_tpu ...`. Talks HTTP to the agent like the
reference CLI does (NOMAD_ADDR / --address).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time


def _client(args):
    from .api.client import ApiClient

    return ApiClient(address=args.address, namespace=args.namespace,
                     token=getattr(args, "token", "") or "")


def _p(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


# -- agent -------------------------------------------------------------------


AGENT_FLAG_KEYS = ("data_dir", "port", "workers", "algorithm",
                   "server_id", "peers", "clients", "region",
                   "authoritative_region", "plugin_dir")


def cmd_agent(args) -> int:
    from .api.http import HTTPAgent
    from .client import Client, ClientConfig
    from .core import Server, ServerConfig
    from .structs.operator import SchedulerConfiguration

    if args.config:
        from .agent_config import apply_to_args, load_agent_config

        file_cfg = load_agent_config(args.config)
        # defaults come from the parser itself (by parsing a bare
        # `agent` invocation — subparser defaults are invisible to the
        # top-level get_default) so the merge can't drift from the
        # declared flag defaults
        defaults_ns = build_parser().parse_args(["agent"])
        defaults = {k: getattr(defaults_ns, k) for k in AGENT_FLAG_KEYS}
        apply_to_args(file_cfg, args, defaults)

    cfg = ServerConfig(
        num_workers=args.workers,
        gossip_key=getattr(args, "gossip_key", "") or "",
        region=getattr(args, "region", "global"),
        authoritative_region=getattr(args, "authoritative_region", ""),
        sched_config=SchedulerConfiguration(scheduler_algorithm=args.algorithm))

    replicated = transport = None
    if args.peers:
        # multi-server mode: raft over the socket transport (reference
        # `nomad agent -server -bootstrap-expect N`)
        from .raft.cluster import ReplicatedServer
        from .raft.transport import SocketTransport

        peers = dict(p.split("=", 1) for p in args.peers.split(","))
        if args.server_id not in peers:
            print(f"--server-id {args.server_id!r} not in --peers", file=sys.stderr)
            return 1
        transport = SocketTransport(args.server_id, peers[args.server_id],
                                    peers).start()
        joining = bool(getattr(args, "join", ""))
        cleanup = getattr(args, "dead_server_cleanup", 0.0) or None
        gossip_bind = getattr(args, "gossip", "") or None
        gossip_seeds = [a for a in
                        (getattr(args, "retry_join", "") or "").split(",")
                        if a]
        replicated = ReplicatedServer(
            args.server_id, list(peers), transport, cfg,
            data_dir=args.data_dir or None,
            bootstrap=not joining and not gossip_seeds,
            dead_server_cleanup_s=cleanup,
            gossip_bind=gossip_bind, gossip_seeds=gossip_seeds)
        replicated.start()
        if joining:
            replicated.join(args.join)
        server = replicated.server
        endpoint = replicated
    else:
        server = Server(cfg)
        server.start()
        endpoint = server

    # HTTP first: the status/leader endpoint must be observable while the
    # clients wait out the initial leader election to register
    http_agent = HTTPAgent(server, port=args.port,
                           writer=replicated).start()
    clients = []
    for i in range(args.clients):
        c = Client(endpoint, ClientConfig(
            data_dir=os.path.join(args.data_dir, f"client{i}")
            if args.data_dir else "",
            plugin_dir=getattr(args, "plugin_dir", "")))
        c.start()
        clients.append(c)
    http_agent.clients = clients  # serve /v1/client/* for local clients
    if replicated is not None:
        # WAN gossip members read this to maintain the region registry
        replicated.set_gossip_http(http_agent.address)
    print(f"agent started: {http_agent.address} "
          f"(workers={args.workers} clients={args.clients} "
          f"algorithm={args.algorithm}"
          + (f" server-id={args.server_id}" if replicated else "") + ")",
          flush=True)
    stop = []
    reload_req = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    if args.config and hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, lambda *a: reload_req.append(1))
    try:
        while not stop:
            if reload_req:
                reload_req.clear()
                # live reload (reference agent.go:1360): the scheduler
                # configuration is the hot-swappable subset
                try:
                    import copy as _copy

                    from .agent_config import load_agent_config

                    fc = load_agent_config(args.config)
                    if fc.algorithm:
                        # mutate only the algorithm on a copy of the
                        # CURRENT config: a reload must not reset
                        # operator-set fields (pause, preemption, ...)
                        new_cfg = _copy.deepcopy(server.sched_config)
                        new_cfg.scheduler_algorithm = fc.algorithm
                        target = replicated if replicated is not None else server
                        target.set_scheduler_config(new_cfg)
                        print(f"config reloaded: algorithm={fc.algorithm}",
                              flush=True)
                except Exception as e:
                    print(f"config reload failed: {e}", flush=True)
            time.sleep(0.2)
    finally:
        http_agent.stop()
        for c in clients:
            c.stop()
        if replicated is not None:
            replicated.stop()
            transport.stop()
        else:
            server.stop()
    return 0


# -- job ---------------------------------------------------------------------


def cmd_job_validate(args) -> int:
    """Parse + validate a jobspec locally (reference
    command/job_validate.go; the API twin is POST /v1/jobs/parse)."""
    from .api.codec import to_dict
    from .api.jobspec import parse_file

    try:
        job = parse_file(args.spec, variables=_spec_vars(args))
    except (OSError, ValueError) as e:
        print(f"Job validation failed: {e}", file=sys.stderr)
        return 1
    if getattr(args, "as_json", False):
        _p(to_dict(job))
    else:
        groups = ", ".join(f"{tg.name}[{tg.count}]"
                           for tg in job.task_groups)
        print(f"Job validation successful: {job.id!r} "
              f"({job.type}; groups: {groups})")
    return 0


def cmd_job_plan(args) -> int:
    """Dry-run the update and print per-group desired changes
    (reference command/job_plan.go)."""
    from .api.jobspec import parse_file

    job = parse_file(args.spec, variables=_spec_vars(args))
    out = _client(args).plan_job(job)
    diff = out.get("diff", {})
    print(f"Job: {out.get('job_id')!r} (version {out.get('job_version')}, "
          f"{diff.get('type', '?')})")
    for f in diff.get("fields", [])[:40]:
        print(f"  ~ {f}")
    print("\nScheduler dry-run:")
    for tg, ann in sorted((out.get("annotations") or {}).items()):
        parts = [f"{k}: {v}" for k, v in sorted(ann.items()) if v]
        print(f"  group {tg!r}: " + (", ".join(parts) if parts else "no changes"))
    failed = out.get("failed_tg_allocs") or {}
    for tg, m in failed.items():
        print(f"  group {tg!r}: {m.get('coalesced_failures', 0) + 1} "
              f"WOULD FAIL to place (filtered {m.get('nodes_filtered')}, "
              f"exhausted {m.get('nodes_exhausted')})")
    return 1 if failed else 0


def _spec_vars(args) -> dict:
    out = {}
    for kv in getattr(args, "var", None) or []:
        if "=" not in kv:
            print(f"invalid -var {kv!r}: expected key=value", file=sys.stderr)
            raise SystemExit(2)
        k, v = kv.split("=", 1)
        out[k] = v
    return out


def cmd_job_run(args) -> int:
    from .api.jobspec import parse_file

    job = parse_file(args.spec, variables=_spec_vars(args))
    eval_id = _client(args).register_job(job)
    print(f"job {job.id!r} registered, evaluation {eval_id}")
    if args.detach:
        return 0
    return _monitor_eval(args, eval_id)


def _monitor_eval(args, eval_id: str) -> int:
    api = _client(args)
    deadline = time.time() + 30
    while time.time() < deadline:
        ev = api.evaluation(eval_id)
        if ev["status"] in ("complete", "failed", "canceled"):
            print(f"evaluation {eval_id} -> {ev['status']} "
                  f"{ev.get('status_description', '')}".strip())
            if ev.get("blocked_eval"):
                print(f"  blocked eval created: {ev['blocked_eval']}")
            for tg, m in (ev.get("failed_tg_allocs") or {}).items():
                print(f"  group {tg!r}: {m.get('coalesced_failures', 0) + 1} "
                      f"unplaced (filtered {m.get('nodes_filtered')}, "
                      f"exhausted {m.get('nodes_exhausted')})")
            return 0 if ev["status"] == "complete" else 1
        time.sleep(0.2)
    print(f"evaluation {eval_id} still in progress")
    return 1


def cmd_job_dispatch(args) -> int:
    """Dispatch a parameterized job (reference command/job_dispatch.go)."""
    payload = b""
    if args.payload_file:
        with open(args.payload_file, "rb") as f:
            payload = f.read()
    meta = dict(kv.split("=", 1) for kv in args.meta or [])
    out = _client(args).dispatch_job(args.job_id, payload=payload, meta=meta)
    print(f"dispatched {out['dispatched_job_id']!r}, "
          f"evaluation {out['eval_id']}")
    if args.detach:
        return 0
    return _monitor_eval(args, out["eval_id"])


def cmd_job_scale(args) -> int:
    eval_id = _client(args).scale_job(args.job_id, args.group, args.count)
    print(f"job {args.job_id!r} group {args.group!r} scaled to "
          f"{args.count}, evaluation {eval_id}")
    return _monitor_eval(args, eval_id) if not args.detach else 0


def cmd_job_revert(args) -> int:
    eval_id = _client(args).revert_job(args.job_id, args.version)
    print(f"job {args.job_id!r} reverted to version {args.version}, "
          f"evaluation {eval_id}")
    return _monitor_eval(args, eval_id) if not args.detach else 0


def cmd_job_history(args) -> int:
    for v in _client(args).job_versions(args.job_id):
        print(f"version {v['version']:4d}  stable={v['stable']}  "
              f"index={v['job_modify_index']}")
    return 0


def cmd_job_status(args) -> int:
    api = _client(args)
    if not args.job_id:
        _p(api.list_jobs())
        return 0
    job = api.job(args.job_id)
    allocs = api.job_allocations(args.job_id)
    print(f"ID       = {job['id']}\nType     = {job['type']}\n"
          f"Priority = {job['priority']}\nStatus   = {job['status']}")
    print("\nAllocations")
    for a in allocs:
        print(f"{a['id'][:8]}  {a['task_group']:12} {a['node_id'][:8]}  "
              f"{a['desired_status']:6} {a['client_status']}")
    return 0


def cmd_job_stop(args) -> int:
    eval_id = _client(args).deregister_job(args.job_id, purge=args.purge)
    print(f"job {args.job_id!r} stopped, evaluation {eval_id}")
    return 0


# -- node --------------------------------------------------------------------


def cmd_node_status(args) -> int:
    api = _client(args)
    if not args.node_id:
        _p(api.list_nodes())
        return 0
    _p(api.node(args.node_id))
    return 0


def cmd_node_drain(args) -> int:
    api = _client(args)
    if args.enable:
        api.drain_node(args.node_id, drain_spec={"deadline_s": args.deadline})
        print(f"node {args.node_id} draining")
    else:
        api.drain_node(args.node_id, drain_spec=None, mark_eligible=True)
        print(f"node {args.node_id} drain disabled")
    return 0


def cmd_node_eligibility(args) -> int:
    _client(args).set_node_eligibility(args.node_id, args.enable)
    print(f"node {args.node_id} "
          f"{'eligible' if args.enable else 'ineligible'}")
    return 0


# -- alloc / eval / operator -------------------------------------------------


def cmd_alloc_status(args) -> int:
    _p(_client(args).allocation(args.alloc_id))
    return 0


def cmd_alloc_stop(args) -> int:
    """Stop and reschedule one allocation (reference command/alloc_stop.go)."""
    eval_id = _client(args).stop_alloc(args.alloc_id)
    print(f"alloc {args.alloc_id} stopping, evaluation {eval_id}")
    return _monitor_eval(args, eval_id) if not args.detach else 0


def cmd_alloc_logs(args) -> int:
    """Print a task's captured output (reference command/alloc_logs.go)."""
    out = _client(args).alloc_logs(
        args.alloc_id, task=args.task,
        log_type="stderr" if args.stderr else "stdout",
        offset=args.offset)
    sys.stdout.write(out["data"].decode(errors="replace"))
    return 0


def cmd_alloc_exec(args) -> int:
    """Interactive command in a running allocation (reference
    command/alloc_exec.go over the exec-session HTTP surface)."""
    import threading

    api = _client(args)
    sid = api.alloc_exec_start(args.alloc_id, args.command, task=args.task,
                               tty=args.tty)
    done = threading.Event()

    def pump_stdin():
        try:
            while not done.is_set():
                line = sys.stdin.readline()
                if not line:
                    api.alloc_exec_stdin(sid, b"", close=True)
                    return
                api.alloc_exec_stdin(sid, line.encode())
        except Exception:
            pass

    t = threading.Thread(target=pump_stdin, daemon=True)
    if not sys.stdin.isatty() or args.interactive:
        t.start()
    offset = 0
    exit_code = 0
    try:
        while True:
            out = api.alloc_exec_output(sid, offset=offset, wait_s=10.0)
            if out["data"]:
                sys.stdout.buffer.write(out["data"])
                sys.stdout.buffer.flush()
            offset = out["offset"]
            if out.get("exited"):
                exit_code = int(out.get("exit_code") or 0)
                break
    finally:
        done.set()
        try:
            api.alloc_exec_close(sid)
        except Exception:
            pass
    return exit_code


def cmd_alloc_fs(args) -> int:
    """Browse/read an allocation's filesystem (reference
    command/alloc_fs.go)."""
    api = _client(args)
    st = api.alloc_fs_stat(args.alloc_id, args.path or "/")
    if st["is_dir"]:
        for e in api.alloc_fs_ls(args.alloc_id, args.path or "/"):
            kind = "d" if e["is_dir"] else "-"
            print(f"{kind} {e['size']:>10}  {e['name']}")
        return 0
    offset = 0
    while True:
        data = api.alloc_fs_cat(args.alloc_id, args.path, offset=offset)
        if not data:
            break
        sys.stdout.buffer.write(data)
        offset += len(data)
    sys.stdout.buffer.flush()
    return 0


def cmd_eval_status(args) -> int:
    _p(_client(args).evaluation(args.eval_id))
    return 0


def cmd_operator_snapshot(args) -> int:
    api = _client(args)
    if args.op == "save":
        data = api.snapshot_save()
        with open(args.file, "w") as f:
            json.dump(data, f)
        print(f"snapshot saved to {args.file} (index {data.get('index')})")
        return 0
    with open(args.file) as f:
        data = json.load(f)
    index = api.snapshot_restore(data)
    print(f"snapshot restored at index {index}")
    return 0


def cmd_operator_debug(args) -> int:
    """Capture a support bundle a maintainer can triage from (reference
    command/operator_debug.go): cluster state, metrics, thread dumps, a
    sampled CPU profile, recent events, and a monitor-log slice, packed
    into one tar.gz."""
    import io
    import tarfile
    import urllib.request

    out_path = args.output or f"nomad-debug-{int(time.time())}.tar.gz"
    dur = max(1.0, min(args.duration, 30.0))
    token = getattr(args, "token", "") or ""

    def _get_json(path: str, timeout: float = 15.0):
        req = urllib.request.Request(f"{args.address}{path}",
                                     headers={"X-Nomad-Token": token})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    captures = {
        "agent_self.json": lambda: _get_json("/v1/agent/self"),
        "leader.json": lambda: _get_json("/v1/status/leader"),
        "members.json": lambda: _get_json("/v1/agent/members"),
        "raft_configuration.json":
            lambda: _get_json("/v1/operator/raft/configuration"),
        "scheduler_config.json":
            lambda: _get_json("/v1/operator/scheduler/configuration"),
        "jobs.json": lambda: _get_json("/v1/jobs"),
        "nodes.json": lambda: _get_json("/v1/nodes"),
        "evals.json": lambda: _get_json("/v1/evaluations"),
        "deployments.json": lambda: _get_json("/v1/deployments"),
        "threads.json": lambda: _get_json("/v1/agent/pprof/threads"),
        "profile.json":
            lambda: _get_json(f"/v1/agent/pprof/profile?seconds={dur}",
                              timeout=dur + 30.0),
    }

    with tarfile.open(out_path, "w:gz") as tar:
        def add(name: str, payload) -> None:
            if isinstance(payload, (dict, list)):
                data = json.dumps(payload, indent=2, default=str).encode()
            else:
                data = str(payload).encode()
            info = tarfile.TarInfo(f"nomad-debug/{name}")
            info.size = len(data)
            info.mtime = int(time.time())
            tar.addfile(info, io.BytesIO(data))

        for name, fn in captures.items():
            try:
                add(name, fn())
            except Exception as e:
                add(name + ".error", f"{type(e).__name__}: {e}")
        # prometheus metrics ride raw (non-JSON body)
        try:
            req = urllib.request.Request(
                f"{args.address}/v1/metrics?format=prometheus",
                headers={"X-Nomad-Token": getattr(args, "token", "") or ""})
            add("metrics.prom",
                urllib.request.urlopen(req, timeout=15).read().decode())
        except Exception as e:
            add("metrics.prom.error", f"{type(e).__name__}: {e}")
        # a short live log slice (the monitor stream)
        try:
            req = urllib.request.Request(
                f"{args.address}/v1/agent/monitor?wait={dur}"
                "&log_level=debug",
                headers={"X-Nomad-Token": getattr(args, "token", "") or ""})
            lines = []
            with urllib.request.urlopen(req, timeout=dur + 15) as resp:
                deadline = time.time() + dur
                while time.time() < deadline:
                    line = resp.readline()
                    if not line:
                        break
                    lines.append(line.decode(errors="replace"))
            add("monitor.log", "".join(lines))
        except Exception as e:
            add("monitor.log.error", f"{type(e).__name__}: {e}")
    print(f"debug bundle written to {out_path}")
    return 0


def cmd_operator_scheduler(args) -> int:
    api = _client(args)
    if args.op == "get-config":
        _p(api.scheduler_configuration())
        return 0
    cfg = dict(api.scheduler_configuration())
    if args.scheduler_algorithm:
        cfg["scheduler_algorithm"] = args.scheduler_algorithm
    api.set_scheduler_configuration(cfg)
    print("scheduler configuration updated")
    return 0


def cmd_service(args) -> int:
    """Service catalog (reference command/service_list.go / service_info.go)."""
    api = _client(args)
    if args.op == "list":
        for s in api.list_services():
            print(f"{s['service_name']}\t{s['instances']} instance(s)\t"
                  f"tags={','.join(s['tags']) or '-'}")
        return 0
    if not args.name:
        print("service info requires a name", file=sys.stderr)
        return 2
    for reg in api.service(args.name):
        print(f"{reg['id']}\t{reg['address']}:{reg['port']}\t"
              f"node={reg['node_id'][:8]}\talloc={reg['alloc_id'][:8]}")
    return 0


def cmd_monitor(args) -> int:
    """Stream agent logs (reference command/monitor.go)."""
    import urllib.error
    import urllib.request

    url = (f"{args.address}/v1/agent/monitor?wait={args.wait}"
           f"&log_level={args.log_level}")
    headers = {}
    token = getattr(args, "token", "")
    if token:
        # agent:read-gated with ACLs on, like every _client() route
        headers["X-Nomad-Token"] = token
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=args.wait + 30) as resp:
            while True:
                line = resp.readline()
                if not line:
                    return 0
                try:
                    rec = json.loads(line)
                    ts = time.strftime("%H:%M:%S",
                                       time.localtime(rec["ts"]))
                    print(f"{ts} [{rec['level']}] {rec['name']}: "
                          f"{rec['message']}", flush=True)
                except (ValueError, KeyError):
                    continue
    except KeyboardInterrupt:
        return 0
    except urllib.error.URLError as e:
        print(f"monitor failed: {e}", file=sys.stderr)
        return 1


def _oidc_login(api, args) -> int:
    """OIDC authorization-code flow (reference command/login.go): start
    a localhost callback listener, hand the user the provider auth URL,
    wait for the redirect, complete the exchange server-side."""
    import secrets as _secrets
    import threading
    import webbrowser
    from http.server import BaseHTTPRequestHandler, HTTPServer
    from urllib.parse import parse_qs, urlparse

    got: dict = {}
    done = threading.Event()

    class CB(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urlparse(self.path)
            if u.path != "/oidc/callback":
                # stray fetches (favicon) must not clobber the code
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            q = parse_qs(u.query)
            got["code"] = (q.get("code") or [""])[0]
            got["state"] = (q.get("state") or [""])[0]
            body = b"Login complete. You can close this tab."
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            done.set()

    srv = HTTPServer(("127.0.0.1", args.callback_port), CB)
    port = srv.server_port
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    redirect_uri = f"http://127.0.0.1:{port}/oidc/callback"
    nonce = _secrets.token_hex(16)
    out, _ = api._request("POST", "/v1/acl/oidc/auth-url", body={
        "auth_method": args.method, "redirect_uri": redirect_uri,
        "client_nonce": nonce})
    url = out["auth_url"]
    if args.no_browser:
        print(f"Open the following URL to authenticate:\n{url}",
              file=sys.stderr, flush=True)
    else:
        print(f"Opening browser for {url}", file=sys.stderr, flush=True)
        webbrowser.open(url)
    if not done.wait(timeout=300.0):
        srv.shutdown()
        print("timed out waiting for the OIDC callback", file=sys.stderr)
        return 1
    srv.shutdown()
    token, _ = api._request("POST", "/v1/acl/oidc/complete-auth", body={
        "auth_method": args.method, "state": got.get("state", ""),
        "code": got.get("code", ""), "redirect_uri": redirect_uri,
        "client_nonce": nonce})
    _p(token)
    return 0


def cmd_acl(args) -> int:
    """ACL operations (reference command/acl_*.go): bootstrap, SSO
    login, auth methods, binding rules."""
    api = _client(args)
    if args.acl_cmd == "bootstrap":
        _p(api._request("POST", "/v1/acl/bootstrap")[0])
        return 0
    if args.acl_cmd == "login":
        if getattr(args, "login_type", "jwt") == "oidc":
            return _oidc_login(api, args)
        if not args.login_token:
            print("acl login -type=jwt requires a login token argument",
                  file=sys.stderr)
            return 2
        token = args.login_token
        if token == "-":
            token = sys.stdin.read().strip()
        _p(api.acl_login(args.method, token))
        return 0
    if args.acl_cmd == "auth-method":
        if args.op == "list":
            _p(api.list_auth_methods())
        elif args.op == "delete":
            api.delete_auth_method(args.name)
            print(f"auth method {args.name} deleted")
        else:  # apply
            body = json.load(open(args.spec)) if args.spec else {}
            api.upsert_auth_method(args.name, body)
            print(f"auth method {args.name} applied")
        return 0
    if args.acl_cmd == "binding-rule":
        if args.op == "list":
            _p(api.list_binding_rules())
        elif args.op == "delete":
            api.delete_binding_rule(args.name)
            print(f"binding rule {args.name} deleted")
        else:
            body = json.load(open(args.spec)) if args.spec else {}
            rid = api.upsert_binding_rule(body)
            print(f"binding rule {rid} applied")
        return 0
    return 2


def cmd_operator_raft(args) -> int:
    """Raft membership operations (reference command/operator_raft_*.go)."""
    api = _client(args)
    if args.op == "list-peers":
        cfg = api.raft_configuration()
        for s in cfg.get("servers", []):
            mark = " (leader)" if s.get("leader") else ""
            print(f"{s['id']}\t{s['address']}{mark}")
        return 0
    if not args.peer_id:
        print("remove-peer requires -peer-id", file=sys.stderr)
        return 2
    api.raft_remove_peer(args.peer_id)
    print(f"peer {args.peer_id} removed")
    return 0


def cmd_region(args) -> int:
    """Federated regions (reference command/regions.go + operator)."""
    api = _client(args)
    if args.op == "list":
        for name in api.get("/v1/regions")[0]:
            print(name)
        return 0
    if args.op == "delete":
        api._request("DELETE", f"/v1/operator/region/{args.name}")
        print(f"region {args.name} deleted")
        return 0
    api._request("POST", f"/v1/operator/region/{args.name}",
                 {"address": args.region_address})
    print(f"region {args.name} -> {args.region_address}")
    return 0


def cmd_server_join(args) -> int:
    """Tell the local agent's server to join a cluster (reference
    command/server_join.go)."""
    api = _client(args)
    api.agent_join(args.join_addr)
    print(f"joined via {args.join_addr}")
    return 0


def cmd_deployment(args) -> int:
    """Deployment operations (reference command/deployment_*.go)."""
    api = _client(args)
    if args.op != "list" and not args.dep_id:
        print(f"deployment {args.op} requires a deployment id",
              file=sys.stderr)
        return 2
    if args.op == "list":
        for d in api.list_deployments():
            print(f"{d['id'][:8]}  {d['job_id']:24} v{d['job_version']}  "
                  f"{d['status']}")
        return 0
    if args.op == "status":
        _p(api.deployment(args.dep_id))
        return 0
    if args.op == "promote":
        eval_id = api.promote_deployment(args.dep_id)
        print(f"deployment {args.dep_id} promoted, evaluation {eval_id}")
        return 0
    api.fail_deployment(args.dep_id)
    print(f"deployment {args.dep_id} failed")
    return 0


# -- namespaces / pools / vars / system --------------------------------------


def cmd_namespace(args) -> int:
    api = _client(args)
    if args.op == "list":
        for n in api.list_namespaces():
            print(f"{n['name']:20} {n.get('description', '')}")
    elif args.op == "apply":
        api.apply_namespace(args.name, args.description)
        print(f"namespace {args.name!r} applied")
    else:
        api.delete_namespace(args.name)
        print(f"namespace {args.name!r} deleted")
    return 0


def cmd_node_pool(args) -> int:
    api = _client(args)
    if args.op == "list":
        for p in api.list_node_pools():
            sc = p.get("scheduler_configuration") or {}
            print(f"{p['name']:20} {p.get('description', '')} "
                  f"{('alg=' + sc['scheduler_algorithm']) if sc.get('scheduler_algorithm') else ''}")
    elif args.op == "apply":
        body = {"description": args.description}
        if args.scheduler_algorithm:
            body["scheduler_configuration"] = {
                "scheduler_algorithm": args.scheduler_algorithm}
        api.apply_node_pool(args.name, body)
        print(f"node pool {args.name!r} applied")
    else:
        api.delete_node_pool(args.name)
        print(f"node pool {args.name!r} deleted")
    return 0


def cmd_var(args) -> int:
    api = _client(args)
    if args.op == "list":
        for v in api.list_variables():
            print(v)
    elif args.op == "get":
        _p(api.get_variable(args.path))
    elif args.op == "put":
        items = dict(kv.split("=", 1) for kv in args.items)
        api.put_variable(args.path, items)
        print(f"var {args.path!r} written")
    else:
        api.delete_variable(args.path)
        print(f"var {args.path!r} deleted")
    return 0


def cmd_volume(args) -> int:
    api = _client(args)
    if args.op == "list":
        for v in api.list_volumes():
            print(f"{v['id']:24} {v['access_mode']:24} claims={v['claims']}")
    elif args.op == "register":
        body = {"name": args.vol_id, "access_mode": args.access_mode}
        api.register_volume(args.vol_id, body)
        print(f"volume {args.vol_id!r} registered")
    else:
        api.deregister_volume(args.vol_id, force=args.force)
        print(f"volume {args.vol_id!r} deregistered")
    return 0


def cmd_system_gc(args) -> int:
    _p(_client(args).system_gc())
    return 0


# -- parser ------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("--address", default=os.environ.get("NOMAD_ADDR",
                                                       "http://127.0.0.1:4646"))
    p.add_argument("--namespace", default=os.environ.get("NOMAD_NAMESPACE",
                                                         "default"))
    p.add_argument("--token", default=os.environ.get("NOMAD_TOKEN", ""),
                   help="ACL secret (X-Nomad-Token; env NOMAD_TOKEN)")
    sub = p.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser("agent", help="run an agent (server+clients+http)")
    ag.add_argument("-dev", action="store_true", dest="dev")
    ag.add_argument("-config", "--config", default="",
                    help="agent config file (HCL-shaped or .json); "
                         "flags override file values; SIGHUP reloads")
    ag.add_argument("--clients", type=int, default=1)
    ag.add_argument("--workers", type=int, default=2)
    ag.add_argument("--port", type=int, default=4646)
    ag.add_argument("--algorithm", default="binpack")
    ag.add_argument("--data-dir", default="")
    ag.add_argument("--region", default="global",
                    help="this cluster's federation region name")
    ag.add_argument("--authoritative-region", dest="authoritative_region",
                    default="", help="region to replicate ACL metadata from")
    ag.add_argument("--plugin-dir", default="",
                    help="directory of external driver plugin executables")
    ag.add_argument("--server-id", default="server-0",
                    help="this server's id in a multi-server cluster")
    ag.add_argument("--peers", default="",
                    help="raft peer set 'id=host:port,id=host:port,...' "
                         "(enables multi-server mode)")
    ag.add_argument("--join", default="",
                    help="address of any live cluster member; this server "
                         "joins that cluster instead of bootstrapping "
                         "(use with --peers listing only itself)")
    ag.add_argument("--gossip", default="",
                    help="gossip bind addr host:port (enables serf-style "
                         "membership, reference nomad/serf.go)")
    ag.add_argument("--retry-join", dest="retry_join", default="",
                    help="comma-separated gossip seed addresses to join via")
    ag.add_argument("--gossip-key", dest="gossip_key", default="",
                    help="shared secret authenticating gossip datagrams")
    ag.add_argument("--dead-server-cleanup", type=float, default=0.0,
                    help="autopilot: remove a server unreachable this many "
                         "seconds (0 = disabled; reference nomad/autopilot.go)")
    ag.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job").add_subparsers(dest="job_cmd", required=True)
    jr = job.add_parser("run")
    jr.add_argument("spec")
    jr.add_argument("-detach", action="store_true")
    jr.add_argument("-var", action="append", dest="var",
                    help="key=value jobspec variable (repeatable)")
    jr.set_defaults(fn=cmd_job_run)
    jp = job.add_parser("plan")
    jp.add_argument("spec")
    jp.add_argument("-var", action="append", dest="var")
    jp.set_defaults(fn=cmd_job_plan)
    jv = job.add_parser("validate", help="parse + validate a jobspec "
                        "without submitting (reference job validate)")
    jv.add_argument("spec")
    jv.add_argument("-var", action="append", dest="var")
    jv.add_argument("-json", action="store_true", dest="as_json",
                    help="print the canonical parsed job as JSON")
    jv.set_defaults(fn=cmd_job_validate)
    jd = job.add_parser("dispatch")
    jd.add_argument("job_id")
    jd.add_argument("--payload-file", default="")
    jd.add_argument("--meta", action="append",
                    help="key=value dispatch metadata (repeatable)")
    jd.add_argument("-detach", action="store_true")
    jd.set_defaults(fn=cmd_job_dispatch)
    jsc = job.add_parser("scale")
    jsc.add_argument("job_id")
    jsc.add_argument("group")
    jsc.add_argument("count", type=int)
    jsc.add_argument("-detach", action="store_true")
    jsc.set_defaults(fn=cmd_job_scale)
    jrv = job.add_parser("revert")
    jrv.add_argument("job_id")
    jrv.add_argument("version", type=int)
    jrv.add_argument("-detach", action="store_true")
    jrv.set_defaults(fn=cmd_job_revert)
    jh = job.add_parser("history")
    jh.add_argument("job_id")
    jh.set_defaults(fn=cmd_job_history)
    js = job.add_parser("status")
    js.add_argument("job_id", nargs="?", default="")
    js.set_defaults(fn=cmd_job_status)
    jst = job.add_parser("stop")
    jst.add_argument("job_id")
    jst.add_argument("-purge", action="store_true")
    jst.set_defaults(fn=cmd_job_stop)

    node = sub.add_parser("node").add_subparsers(dest="node_cmd", required=True)
    ns = node.add_parser("status")
    ns.add_argument("node_id", nargs="?", default="")
    ns.set_defaults(fn=cmd_node_status)
    nd = node.add_parser("drain")
    nd.add_argument("node_id")
    g = nd.add_mutually_exclusive_group(required=True)
    g.add_argument("-enable", action="store_true", dest="enable")
    g.add_argument("-disable", action="store_false", dest="enable")
    nd.add_argument("--deadline", type=float, default=3600.0)
    nd.set_defaults(fn=cmd_node_drain)
    ne = node.add_parser("eligibility")
    ne.add_argument("node_id")
    g2 = ne.add_mutually_exclusive_group(required=True)
    g2.add_argument("-enable", action="store_true", dest="enable")
    g2.add_argument("-disable", action="store_false", dest="enable")
    ne.set_defaults(fn=cmd_node_eligibility)

    al = sub.add_parser("alloc").add_subparsers(dest="alloc_cmd", required=True)
    als = al.add_parser("status")
    als.add_argument("alloc_id")
    als.set_defaults(fn=cmd_alloc_status)
    alstop = al.add_parser("stop")
    alstop.add_argument("alloc_id")
    alstop.add_argument("-detach", action="store_true")
    alstop.set_defaults(fn=cmd_alloc_stop)
    allog = al.add_parser("logs")
    allog.add_argument("alloc_id")
    allog.add_argument("task", nargs="?", default="")
    allog.add_argument("-stderr", action="store_true")
    allog.add_argument("--offset", type=int, default=0)
    allog.set_defaults(fn=cmd_alloc_logs)
    alex = al.add_parser("exec")
    alex.add_argument("-task", default="")
    alex.add_argument("-tty", action="store_true")
    alex.add_argument("-i", dest="interactive", action="store_true",
                      help="forward stdin when attached to a terminal")
    alex.add_argument("alloc_id")
    alex.add_argument("command", nargs="+")
    alex.set_defaults(fn=cmd_alloc_exec)
    alfs = al.add_parser("fs")
    alfs.add_argument("alloc_id")
    alfs.add_argument("path", nargs="?", default="/")
    alfs.set_defaults(fn=cmd_alloc_fs)

    ev = sub.add_parser("eval").add_subparsers(dest="eval_cmd", required=True)
    evs = ev.add_parser("status")
    evs.add_argument("eval_id")
    evs.set_defaults(fn=cmd_eval_status)

    dep = sub.add_parser("deployment")
    dep.add_argument("op", choices=["list", "status", "promote", "fail"])
    dep.add_argument("dep_id", nargs="?", default="")
    dep.set_defaults(fn=cmd_deployment)

    nsp = sub.add_parser("namespace")
    nsp.add_argument("op", choices=["list", "apply", "delete"])
    nsp.add_argument("name", nargs="?", default="")
    nsp.add_argument("-description", default="")
    nsp.set_defaults(fn=cmd_namespace)

    npool = sub.add_parser("node-pool")
    npool.add_argument("op", choices=["list", "apply", "delete"])
    npool.add_argument("name", nargs="?", default="")
    npool.add_argument("-description", default="")
    npool.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                       default="")
    npool.set_defaults(fn=cmd_node_pool)

    var = sub.add_parser("var")
    var.add_argument("op", choices=["list", "get", "put", "delete"])
    var.add_argument("path", nargs="?", default="")
    var.add_argument("items", nargs="*", help="key=value (for put)")
    var.set_defaults(fn=cmd_var)

    vol = sub.add_parser("volume")
    vol.add_argument("op", choices=["list", "register", "deregister"])
    vol.add_argument("vol_id", nargs="?", default="")
    vol.add_argument("-access-mode", dest="access_mode",
                     default="single-node-writer")
    vol.add_argument("-force", action="store_true")
    vol.set_defaults(fn=cmd_volume)

    system = sub.add_parser("system").add_subparsers(dest="system_cmd",
                                                     required=True)
    sgc = system.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)

    op = sub.add_parser("operator").add_subparsers(dest="op_cmd", required=True)
    osched = op.add_parser("scheduler")
    osched.add_argument("op", choices=["get-config", "set-config"])
    osched.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                        default="")
    osched.set_defaults(fn=cmd_operator_scheduler)
    osnap = op.add_parser("snapshot")
    osnap.add_argument("op", choices=["save", "restore"])
    osnap.add_argument("file")
    osnap.set_defaults(fn=cmd_operator_snapshot)
    oraft = op.add_parser("raft")
    oraft.add_argument("op", choices=["list-peers", "remove-peer"])
    oraft.add_argument("-peer-id", dest="peer_id", default="")
    oraft.set_defaults(fn=cmd_operator_raft)
    odebug = op.add_parser("debug", help="capture a support bundle")
    odebug.add_argument("-output", default="",
                        help="bundle path (default nomad-debug-<ts>.tar.gz)")
    odebug.add_argument("-duration", type=float, default=5.0,
                        help="seconds of CPU profile + log capture")
    odebug.set_defaults(fn=cmd_operator_debug)

    mon = sub.add_parser("monitor")
    mon.add_argument("-log-level", dest="log_level", default="info")
    mon.add_argument("-wait", type=int, default=600)
    mon.set_defaults(fn=cmd_monitor)

    aclp = sub.add_parser("acl").add_subparsers(dest="acl_cmd", required=True)
    ab = aclp.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl)
    alog = aclp.add_parser("login")
    alog.add_argument("-method", required=True)
    alog.add_argument("-type", dest="login_type", default="jwt",
                      choices=("jwt", "oidc"),
                      help="jwt: exchange a provided JWT; oidc: browser "
                           "authorization-code flow with a local callback")
    alog.add_argument("-callback-port", type=int, default=0,
                      help="oidc: local callback port (0 = ephemeral)")
    alog.add_argument("-no-browser", action="store_true",
                      help="oidc: print the auth URL instead of opening "
                           "a browser")
    alog.add_argument("login_token", nargs="?", default="",
                      help="external JWT ('-' reads from stdin; "
                           "jwt type only)")
    alog.set_defaults(fn=cmd_acl)
    for kind in ("auth-method", "binding-rule"):
        ap = aclp.add_parser(kind)
        ap.add_argument("op", choices=["apply", "list", "delete"])
        ap.add_argument("name", nargs="?", default="")
        ap.add_argument("-spec", default="",
                        help="JSON config file for apply")
        ap.set_defaults(fn=cmd_acl)

    svc = sub.add_parser("service")
    svc.add_argument("op", choices=["list", "info"])
    svc.add_argument("name", nargs="?", default="")
    svc.set_defaults(fn=cmd_service)

    reg = sub.add_parser("region")
    reg.add_argument("op", choices=["list", "apply", "delete"])
    reg.add_argument("name", nargs="?", default="")
    # dest must NOT collide with the global --address (the agent to
    # talk to) or apply would target the region being registered
    reg.add_argument("-region-address", dest="region_address", default="")
    reg.set_defaults(fn=cmd_region)

    server = sub.add_parser("server").add_subparsers(dest="server_cmd",
                                                     required=True)
    sjoin = server.add_parser("join")
    sjoin.add_argument("join_addr")
    sjoin.set_defaults(fn=cmd_server_join)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
