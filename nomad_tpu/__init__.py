"""nomad_tpu — a TPU-native distributed workload orchestrator.

A brand-new framework with the capabilities of HashiCorp Nomad (the
reference implementation surveyed in SURVEY.md): jobs / task groups /
allocations, pluggable feasibility constraints, binpack / spread scoring,
preemption, deployments, an optimistically-concurrent eval broker + serialized
plan applier over MVCC replicated state, and a client execution plane with
pluggable task drivers.

It is *not* a port. The scheduling hot path — feasibility masking, scoring,
and global assignment — runs as batched JAX/XLA kernels (`nomad_tpu.ops`)
operating on dense (evals x nodes) tensors produced by the tensorization
layer (`nomad_tpu.tensor`), exposed as the pluggable scheduler algorithm
``"tpu-binpack"`` alongside the classic per-node greedy path
(``"binpack"`` / ``"spread"``, reference: nomad/structs/operator.go:199-255).
"""

__version__ = "0.1.0"
